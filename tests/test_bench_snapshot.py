"""The bench's TPU-snapshot fallback (bench._emit_tpu_snapshot): the driver's
perf artifact depends on this path whenever the accelerator tunnel is wedged,
so its gating rules are pinned here — a snapshot only stands in for the SAME
workload, only ever replays a real TPU capture, prefers the newest stamp, and
always discloses its provenance.
"""

import json

import pytest

import bench


def _capture(n=100_000, platform="tpu", value=100.9, stamp="2026-07-29T14:06:21Z"):
    return {
        "metric": f"churn_resolution_ms_n{n}_churn5pct",
        "value": value,
        "unit": "ms",
        "platform": platform,
        "n_members": n,
        "captured_at": stamp,
    }


def _emit(monkeypatch, capsys, files, env=None):
    """Run _emit_tpu_snapshot against a synthetic evidence set; returns the
    (bool result, parsed stdout JSON or None)."""
    # Scrub ambient bench env (a capture/sweep session exports these): the
    # synthetic evidence set must be the only input.
    for name in ("RAPID_TPU_BENCH_SNAPSHOT", "RAPID_TPU_BENCH_N"):
        monkeypatch.delenv(name, raising=False)
    for name, value in (env or {}).items():
        monkeypatch.setenv(name, value)
    monkeypatch.setattr(
        bench.glob, "glob", lambda pattern: [str(p) for p in files]
    )
    ok = bench._emit_tpu_snapshot()
    out = capsys.readouterr().out.strip()
    return ok, (json.loads(out) if out else None)


def test_replays_newest_tpu_capture_with_provenance(tmp_path, monkeypatch, capsys):
    old = tmp_path / "old.json"
    old.write_text(json.dumps(_capture(value=140.0, stamp="2026-07-28T10:00:00Z")))
    new = tmp_path / "new.json"
    new.write_text(json.dumps(_capture(value=100.9, stamp="2026-07-29T14:06:21Z")))

    ok, data = _emit(monkeypatch, capsys, [old, new])
    assert ok
    assert data["value"] == 100.9  # newest stamp wins, not best value
    assert data["platform"] == "tpu"
    # A replay must be distinguishable from a live run.
    assert data["capture"] == "session_snapshot"
    assert data["live_attempt"] == "wedged"
    assert data["snapshot_path"]
    assert data["captured_at"] == "2026-07-29T14:06:21Z"


def test_stale_snapshot_is_self_describing(tmp_path, monkeypatch, capsys):
    # A snapshot whose git_rev differs from HEAD (or is absent) measured
    # different code: the replay must rename the metric, flag stale_code,
    # and demote vs_baseline so nothing downstream reads it as current.
    cap = _capture()
    cap["git_rev"] = "0000000"  # never the current HEAD
    cap["vs_baseline"] = 4.957
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(cap))
    ok, data = _emit(monkeypatch, capsys, [f])
    assert ok
    assert data["stale_code"] is True
    assert data["metric"].endswith("_snapshot")
    assert "vs_baseline" not in data
    assert data["vs_baseline_at_capture"] == 4.957
    assert data["git_rev"] == "0000000"
    assert data["head_rev"] not in (None, "0000000")


def test_unstamped_snapshot_counts_as_stale(tmp_path, monkeypatch, capsys):
    # Round-2 captures predate the git_rev stamp: unknown provenance is
    # treated as stale, never silently trusted.
    cap = _capture()
    cap["vs_baseline"] = 4.957
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(cap))
    ok, data = _emit(monkeypatch, capsys, [f])
    assert ok
    assert data["stale_code"] is True
    assert data["metric"].endswith("_snapshot")
    assert "vs_baseline" not in data


def test_current_rev_snapshot_keeps_its_metric(tmp_path, monkeypatch, capsys):
    # Same-commit replays (the watcher captured during THIS session) are
    # real measurements of HEAD: metric and vs_baseline survive untouched.
    import os

    head = bench._git_head_rev(os.path.dirname(os.path.abspath(bench.__file__)))
    cap = _capture()
    cap["git_rev"] = head
    cap["vs_baseline"] = 4.957
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(cap))
    ok, data = _emit(monkeypatch, capsys, [f])
    assert ok
    if head is None:  # no git in the environment: stale is the safe answer
        assert data["stale_code"] is True
    else:
        assert data["stale_code"] is False
        assert not data["metric"].endswith("_snapshot")
        assert data["vs_baseline"] == 4.957


def test_evidence_only_commits_do_not_stale_a_snapshot(tmp_path):
    # The watcher commits its own capture right after stamping it, advancing
    # HEAD past the captured rev with a byte-identical source tree. Staleness
    # is decided by diffing the measurement paths, not by rev equality.
    import subprocess

    def git(*args):
        return subprocess.run(
            ["git", "-c", "user.name=t", "-c", "user.email=t@t", *args],
            cwd=tmp_path, capture_output=True, text=True, check=True,
        ).stdout.strip()

    git("init", "-q")
    (tmp_path / "bench.py").write_text("x = 1\n")
    (tmp_path / "rapid_tpu").mkdir()
    (tmp_path / "rapid_tpu" / "core.py").write_text("y = 1\n")
    git("add", "-A")
    git("commit", "-qm", "code")
    measured_rev = git("rev-parse", "--short", "HEAD")
    (tmp_path / "evidence").mkdir()
    (tmp_path / "evidence" / "bench.json").write_text("{}\n")
    git("add", "-A")
    git("commit", "-qm", "evidence only")
    head_after_evidence = git("rev-parse", "--short", "HEAD")
    root = str(tmp_path)
    assert not bench._snapshot_is_stale(root, measured_rev, head_after_evidence)
    # A code commit after the capture DOES stale it.
    (tmp_path / "rapid_tpu" / "core.py").write_text("y = 2\n")
    git("add", "-A")
    git("commit", "-qm", "code change")
    head_after_code = git("rev-parse", "--short", "HEAD")
    assert bench._snapshot_is_stale(root, measured_rev, head_after_code)
    # Unknown / unverifiable provenance is always stale.
    assert bench._snapshot_is_stale(root, None, head_after_code)
    assert bench._snapshot_is_stale(root, "fffffff", head_after_code)
    assert bench._snapshot_is_stale(root, measured_rev, None)


def test_never_replays_a_different_workload(tmp_path, monkeypatch, capsys):
    # A smoke run at N=2000 must not replay the 100K capture, and vice versa.
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(_capture(n=100_000)))
    ok, data = _emit(
        monkeypatch, capsys, [f], env={"RAPID_TPU_BENCH_N": "2000"}
    )
    assert not ok and data is None


def test_never_replays_a_cpu_measurement(tmp_path, monkeypatch, capsys):
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(_capture(platform="cpu")))
    ok, data = _emit(monkeypatch, capsys, [f])
    assert not ok and data is None


@pytest.mark.parametrize("content", ["", "not json{", json.dumps(["list"]),
                                     json.dumps({"platform": "tpu"})])
def test_tolerates_malformed_or_incomplete_candidates(
    content, tmp_path, monkeypatch, capsys
):
    # Corrupt/incomplete files are skipped, never crash the fallback.
    bad = tmp_path / "bad.json"
    bad.write_text(content)
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_capture()))
    ok, data = _emit(monkeypatch, capsys, [bad, good])
    assert ok and data["value"] == 100.9


def test_explicit_snapshot_env_overrides_discovery(tmp_path, monkeypatch, capsys):
    chosen = tmp_path / "chosen.json"
    chosen.write_text(json.dumps(_capture(value=88.8)))
    ignored = tmp_path / "ignored.json"
    ignored.write_text(json.dumps(_capture(value=55.5, stamp="2026-07-30T00:00:00Z")))

    # Discovery must not even run (glob would only find the 'ignored' file).
    ok, data = _emit(
        monkeypatch, capsys, [ignored],
        env={"RAPID_TPU_BENCH_SNAPSHOT": str(chosen)},
    )
    assert ok and data["value"] == 88.8


def test_autotuned_lanes_resolution(tmp_path, monkeypatch):
    # Width resolution order: env override first; else newest committed
    # autotune evidence, nearest measured shape; else the default. Garbage
    # lines and non-TPU or insane widths never poison the choice.
    for name in ("RAPID_TPU_BENCH_LANES", "RAPID_TPU_BENCH_LANES_1M"):
        monkeypatch.delenv(name, raising=False)
    evdir = tmp_path / "evidence" / "round9"
    evdir.mkdir(parents=True)
    (evdir / "autotune.jsonl").write_text(
        json.dumps({"platform": "tpu", "best_width": 999}) + "\n"  # no shape: skipped
        + json.dumps({"platform": "tpu", "shape": [64, 100_000], "best_width": 256}) + "\n"
        + json.dumps({"platform": "tpu", "shape": [8, 1_000_000], "best_width": 512}) + "\n"
        + json.dumps({"platform": "cpu", "shape": [64, 100_000], "best_width": 1024}) + "\n"
        + json.dumps({"platform": "tpu", "shape": [8, 500_000], "best_width": 7}) + "\n"
        + "not json{\n"
    )
    monkeypatch.setattr(
        bench.glob, "glob", lambda pattern: [str(evdir / "autotune.jsonl")]
    )
    MAIN, XL = "RAPID_TPU_BENCH_LANES", "RAPID_TPU_BENCH_LANES_1M"
    assert bench._autotuned_lanes(100_000, MAIN) == 256   # exact shape
    assert bench._autotuned_lanes(90_000, MAIN) == 256    # nearest shape
    assert bench._autotuned_lanes(1_000_000, XL) == 512
    # The sweep plumbs per-point widths through the MAIN env at any N.
    monkeypatch.setenv(MAIN, "1024")
    assert bench._autotuned_lanes(100_000, MAIN) == 1024  # env wins
    assert bench._autotuned_lanes(1_000_000, MAIN) == 1024
    monkeypatch.setenv(XL, "128")
    assert bench._autotuned_lanes(1_000_000, XL) == 128


def test_autotuned_lanes_shape_proximity_guard(tmp_path, monkeypatch):
    # A tuned width only transfers to shapes within 4x of where it was
    # measured: a 2K smoke run must not inherit the 100K-tuned width (the
    # tiling economics don't carry), but 25K-400K legitimately may.
    for name in ("RAPID_TPU_BENCH_LANES", "RAPID_TPU_BENCH_LANES_1M"):
        monkeypatch.delenv(name, raising=False)
    evdir = tmp_path / "evidence" / "round9"
    evdir.mkdir(parents=True)
    (evdir / "autotune.jsonl").write_text(
        json.dumps({"platform": "tpu", "shape": [64, 100_000], "best_width": 512}) + "\n"
    )
    monkeypatch.setattr(
        bench.glob, "glob", lambda pattern: [str(evdir / "autotune.jsonl")]
    )
    MAIN = "RAPID_TPU_BENCH_LANES"
    assert bench._autotuned_lanes(2_000, MAIN) == 128       # far below: default
    assert bench._autotuned_lanes(25_000, MAIN) == 512      # 4x boundary: applies
    assert bench._autotuned_lanes(400_000, MAIN) == 512     # 4x boundary: applies
    assert bench._autotuned_lanes(1_000_000, MAIN) == 128   # far above: default
    monkeypatch.setenv(MAIN, "256")
    assert bench._autotuned_lanes(2_000, MAIN) == 256       # env always wins


def test_autotuned_lanes_eligibility_before_nearest(tmp_path, monkeypatch):
    # Eligibility (4x window) filters BEFORE nearest-shape selection: at
    # N=450K with 100K and 1M both tuned, 100K is nearer by absolute
    # distance but out of window — the in-window 1M width must apply, not
    # the default.
    for name in ("RAPID_TPU_BENCH_LANES", "RAPID_TPU_BENCH_LANES_1M"):
        monkeypatch.delenv(name, raising=False)
    evdir = tmp_path / "evidence" / "round9"
    evdir.mkdir(parents=True)
    (evdir / "autotune.jsonl").write_text(
        json.dumps({"platform": "tpu", "shape": [64, 100_000], "best_width": 512}) + "\n"
        + json.dumps({"platform": "tpu", "shape": [8, 1_000_000], "best_width": 256}) + "\n"
    )
    monkeypatch.setattr(
        bench.glob, "glob", lambda pattern: [str(evdir / "autotune.jsonl")]
    )
    MAIN = "RAPID_TPU_BENCH_LANES"
    assert bench._autotuned_lanes(450_000, MAIN) == 256   # only 1M in window
    assert bench._autotuned_lanes(200_000, MAIN) == 512   # both in window; 100K nearer by ratio


def test_autotuned_lanes_defaults_without_evidence(monkeypatch):
    for name in ("RAPID_TPU_BENCH_LANES", "RAPID_TPU_BENCH_LANES_1M"):
        monkeypatch.delenv(name, raising=False)
    monkeypatch.setattr(bench.glob, "glob", lambda pattern: [])
    assert bench._autotuned_lanes(100_000, "RAPID_TPU_BENCH_LANES") == 128


# ---------------------------------------------------------------------------
# _snapshot_is_stale edge cases: hostile / degenerate provenance
# ---------------------------------------------------------------------------


def test_stale_rejects_non_hex_and_non_string_revs(tmp_path):
    # Provenance comes from a JSON file: anything that is not a plain hex
    # rev must read as stale WITHOUT reaching the git argv (a leading-dash
    # string would parse as a git option; a non-string would crash).
    root = str(tmp_path)  # deliberately not a git repo
    for snap_rev in ("--upload-pack=/bin/true", "HEAD", "main~1", "", "zzzzzzz",
                     1234567, None, ["abc1234"], "abc123"):  # 6 hex chars: too short
        assert bench._snapshot_is_stale(root, snap_rev, "abc1234") is True


def test_stale_when_snapshot_rev_missing_from_repo(tmp_path):
    # A well-formed hex rev that the repo has never seen (force-pushed away,
    # or from another clone) cannot be verified: stale.
    import subprocess

    def git(*args):
        return subprocess.run(
            ["git", "-c", "user.name=t", "-c", "user.email=t@t", *args],
            cwd=tmp_path, capture_output=True, text=True, check=True,
        ).stdout.strip()

    git("init", "-q")
    (tmp_path / "bench.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    head = git("rev-parse", "--short", "HEAD")
    assert bench._snapshot_is_stale(str(tmp_path), "feedfacecafe", head) is True
    assert bench._snapshot_is_stale(str(tmp_path), head, head) is False


def test_hash_root_only_changes_stale_a_snapshot(tmp_path):
    # native/ is a measurement path: a change there (and ONLY there) must
    # stale the snapshot even though bench.py and rapid_tpu/ are untouched.
    import subprocess

    def git(*args):
        return subprocess.run(
            ["git", "-c", "user.name=t", "-c", "user.email=t@t", *args],
            cwd=tmp_path, capture_output=True, text=True, check=True,
        ).stdout.strip()

    git("init", "-q")
    (tmp_path / "bench.py").write_text("x = 1\n")
    (tmp_path / "native").mkdir()
    (tmp_path / "native" / "lib.c").write_text("int x = 1;\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    measured = git("rev-parse", "--short", "HEAD")
    (tmp_path / "native" / "lib.c").write_text("int x = 2;\n")
    git("add", "-A")
    git("commit", "-qm", "native change")
    head = git("rev-parse", "--short", "HEAD")
    assert bench._snapshot_is_stale(str(tmp_path), measured, head) is True


# ---------------------------------------------------------------------------
# Derived bench metrics: units audited, plausibility bounds pinned
# ---------------------------------------------------------------------------


def test_derived_metrics_formulas_at_engine_grain():
    # The default workload at the r03 snapshot's wall-clock. The engine
    # delivers per COHORT (C delivered-bit sets per alert), not per member:
    # the old N-multiplied formula produced the implausible 4.96e10/s figure
    # flagged across BENCH_r03-r05.
    d = bench.derived_metrics(
        n=100_000, n_join=2500, n_crash=2500, k_rings=10, cohorts=64,
        value_ms=100.875,
    )
    assert d["alerts_fired"] == 5000 * 10
    assert d["alerts_per_sec"] == round(50_000 / 0.100875, 0)
    assert d["alert_deliveries_per_sec"] == round(50_000 * 64 / 0.100875, 0)
    # The delivery rate is alerts x cohorts — never x N (each rate rounds
    # independently, so the identity holds to rounding slack).
    assert abs(d["alert_deliveries_per_sec"] - 64 * d["alerts_per_sec"]) <= 64


@pytest.mark.parametrize("value_ms", [10.0, 100.875, 500.0, 60_000.0])
def test_derived_metrics_plausibility_bounds(value_ms):
    # Any resolution between 10 ms (4x the r03 hardware number — far below
    # any credible future point) and a minute at the default workload must
    # yield physically plausible rates: alerts bounded by churn x K, and
    # deliveries under 1e9/s (no chip or network moves more distinct alert
    # deliveries than that at these Ns — the 4.96e10 figure could never
    # have passed this pin).
    d = bench.derived_metrics(
        n=100_000, n_join=2500, n_crash=2500, k_rings=10, cohorts=64,
        value_ms=value_ms,
    )
    assert 0 < d["alerts_per_sec"] <= 5_000 * 10 * 1000  # >= 1 ms resolution
    assert d["alert_deliveries_per_sec"] < 1e9
    assert abs(d["alert_deliveries_per_sec"] - d["alerts_per_sec"] * 64) <= 64


def test_derived_metrics_reject_degenerate_wallclock():
    with pytest.raises(ValueError, match="positive"):
        bench.derived_metrics(
            n=100, n_join=1, n_crash=1, k_rings=10, cohorts=4, value_ms=0.0
        )



def test_hlo_audit_summary_embeds_per_entrypoint_budget_table():
    # The bench's hlo_audit stage embeds this table in the metric JSON:
    # one row per registered entrypoint with the collective counts the
    # perfview trajectory diffs (hlo-drift), plus temp memory and donation
    # outcomes. Compiles ride the process-wide session cache shared with
    # the staticcheck gate, so this costs nothing extra in a full session.
    table = bench.hlo_audit_summary()
    assert "error" not in table, table
    assert {"step", "run_to_decision", "run_until_membership", "sync",
            "step_compact", "step_telem", "step_trace",
            "sharded_step", "sharded_step_telem", "sharded_wave",
            "sharded2d_wave",
            "fleet3d_step", "fleet3d_wave"} == set(table)
    for name, row in table.items():
        assert set(row) == {
            "collectives", "collective_bytes", "hot_loop_collectives",
            "hot_loop_bytes", "temp_bytes", "argument_bytes",
            "donation_dropped",
        }, name
        assert row["donation_dropped"] == 0, name
    # The compaction saving is visible in the embedded table (the bench's
    # memory_report keys its mem_status off exactly this pair).
    assert (
        table["step_compact"]["argument_bytes"]
        < table["step"]["argument_bytes"]
    )
    # Sharded programs communicate; single-device ones must not.
    assert table["sharded_wave"]["hot_loop_collectives"] > 0
    assert table["step"]["collectives"] == 0
