"""The bench's TPU-snapshot fallback (bench._emit_tpu_snapshot): the driver's
perf artifact depends on this path whenever the accelerator tunnel is wedged,
so its gating rules are pinned here — a snapshot only stands in for the SAME
workload, only ever replays a real TPU capture, prefers the newest stamp, and
always discloses its provenance.
"""

import json

import pytest

import bench


def _capture(n=100_000, platform="tpu", value=100.9, stamp="2026-07-29T14:06:21Z"):
    return {
        "metric": f"churn_resolution_ms_n{n}_churn5pct",
        "value": value,
        "unit": "ms",
        "platform": platform,
        "n_members": n,
        "captured_at": stamp,
    }


def _emit(monkeypatch, capsys, files, env=None):
    """Run _emit_tpu_snapshot against a synthetic evidence set; returns the
    (bool result, parsed stdout JSON or None)."""
    # Scrub ambient bench env (a capture/sweep session exports these): the
    # synthetic evidence set must be the only input.
    for name in ("RAPID_TPU_BENCH_SNAPSHOT", "RAPID_TPU_BENCH_N"):
        monkeypatch.delenv(name, raising=False)
    for name, value in (env or {}).items():
        monkeypatch.setenv(name, value)
    monkeypatch.setattr(
        bench.glob, "glob", lambda pattern: [str(p) for p in files]
    )
    ok = bench._emit_tpu_snapshot()
    out = capsys.readouterr().out.strip()
    return ok, (json.loads(out) if out else None)


def test_replays_newest_tpu_capture_with_provenance(tmp_path, monkeypatch, capsys):
    old = tmp_path / "old.json"
    old.write_text(json.dumps(_capture(value=140.0, stamp="2026-07-28T10:00:00Z")))
    new = tmp_path / "new.json"
    new.write_text(json.dumps(_capture(value=100.9, stamp="2026-07-29T14:06:21Z")))

    ok, data = _emit(monkeypatch, capsys, [old, new])
    assert ok
    assert data["value"] == 100.9  # newest stamp wins, not best value
    assert data["platform"] == "tpu"
    # A replay must be distinguishable from a live run.
    assert data["capture"] == "session_snapshot"
    assert data["live_attempt"] == "wedged"
    assert data["snapshot_path"]
    assert data["captured_at"] == "2026-07-29T14:06:21Z"


def test_never_replays_a_different_workload(tmp_path, monkeypatch, capsys):
    # A smoke run at N=2000 must not replay the 100K capture, and vice versa.
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(_capture(n=100_000)))
    ok, data = _emit(
        monkeypatch, capsys, [f], env={"RAPID_TPU_BENCH_N": "2000"}
    )
    assert not ok and data is None


def test_never_replays_a_cpu_measurement(tmp_path, monkeypatch, capsys):
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(_capture(platform="cpu")))
    ok, data = _emit(monkeypatch, capsys, [f])
    assert not ok and data is None


@pytest.mark.parametrize("content", ["", "not json{", json.dumps(["list"]),
                                     json.dumps({"platform": "tpu"})])
def test_tolerates_malformed_or_incomplete_candidates(
    content, tmp_path, monkeypatch, capsys
):
    # Corrupt/incomplete files are skipped, never crash the fallback.
    bad = tmp_path / "bad.json"
    bad.write_text(content)
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_capture()))
    ok, data = _emit(monkeypatch, capsys, [bad, good])
    assert ok and data["value"] == 100.9


def test_explicit_snapshot_env_overrides_discovery(tmp_path, monkeypatch, capsys):
    chosen = tmp_path / "chosen.json"
    chosen.write_text(json.dumps(_capture(value=88.8)))
    ignored = tmp_path / "ignored.json"
    ignored.write_text(json.dumps(_capture(value=55.5, stamp="2026-07-30T00:00:00Z")))

    # Discovery must not even run (glob would only find the 'ignored' file).
    ok, data = _emit(
        monkeypatch, capsys, [ignored],
        env={"RAPID_TPU_BENCH_SNAPSHOT": str(chosen)},
    )
    assert ok and data["value"] == 88.8
