"""Run-ledger semantics: registered vocabularies enforced at write time,
stage bracketing with durations and failure events, provenance stamping,
and crash-tolerant reading (torn final lines).
"""

import json

import pytest

from rapid_tpu.utils.ledger import (
    STAGE_NAMES,
    LedgerEvent,
    RunLedger,
    code_hash,
    last_completed_stage,
    open_stage,
    provenance,
    read_ledger,
)


def _events(path):
    events, skipped = read_ledger(str(path))
    return events


def test_emit_writes_validated_flushed_lines(tmp_path):
    path = tmp_path / "run.jsonl"
    ledger = RunLedger(str(path), run_id="r1")
    ledger.emit(LedgerEvent.RUN_BEGIN, mode="test")
    ledger.emit(LedgerEvent.RUN_END, outcome="completed")
    # Flushed per line: readable without closing the writer.
    events = _events(path)
    assert [e["event"] for e in events] == ["run_begin", "run_end"]
    assert all(e["run_id"] == "r1" for e in events)
    assert [e["seq"] for e in events] == [0, 1]
    assert all("t_s" in e and "wall" in e and "pid" in e for e in events)
    ledger.close()


def test_emit_rejects_unregistered_vocabulary(tmp_path):
    ledger = RunLedger(str(tmp_path / "run.jsonl"))
    with pytest.raises(TypeError, match="LedgerEvent members"):
        ledger.emit("run_begin")
    with pytest.raises(ValueError, match="unregistered ledger stage"):
        ledger.emit(LedgerEvent.STAGE_BEGIN, stage="made_up_stage")
    assert _events(tmp_path / "run.jsonl") == []  # nothing leaked
    ledger.close()


def test_stage_brackets_success_with_duration(tmp_path):
    path = tmp_path / "run.jsonl"
    ledger = RunLedger(str(path))
    with ledger.stage("state_build", timeout_s=60, n=1024):
        pass
    begin, end = _events(path)
    assert begin["event"] == "stage_begin" and begin["stage"] == "state_build"
    assert begin["timeout_s"] == 60 and begin["n"] == 1024
    assert end["event"] == "stage_end" and end["duration_ms"] >= 0
    assert last_completed_stage(_events(path)) == "state_build"
    assert open_stage(_events(path)) is None
    ledger.close()


def test_stage_failure_emits_stage_fail_and_reraises(tmp_path):
    path = tmp_path / "run.jsonl"
    ledger = RunLedger(str(path))
    with pytest.raises(RuntimeError, match="boom"):
        with ledger.stage("warmup_compile"):
            raise RuntimeError("boom")
    begin, fail = _events(path)
    assert fail["event"] == "stage_fail" and "boom" in fail["error"]
    # A failed stage is not a completed one...
    assert last_completed_stage(_events(path)) is None
    # ...but it is CLOSED: the run is not "stuck in" it.
    assert open_stage(_events(path)) is None
    ledger.close()


def test_open_stage_identifies_the_wedge_point(tmp_path):
    path = tmp_path / "run.jsonl"
    ledger = RunLedger(str(path))
    with ledger.stage("state_build"):
        pass
    ledger.emit(LedgerEvent.STAGE_BEGIN, stage="warmup_compile", timeout_s=900)
    # (process wedges here: no end ever arrives)
    stuck = open_stage(_events(path))
    assert stuck is not None and stuck["stage"] == "warmup_compile"
    assert stuck["timeout_s"] == 900
    assert last_completed_stage(_events(path)) == "state_build"
    ledger.close()


def test_read_ledger_tolerates_torn_and_foreign_lines(tmp_path):
    path = tmp_path / "run.jsonl"
    ledger = RunLedger(str(path))
    ledger.emit(LedgerEvent.RUN_BEGIN)
    ledger.close()
    with open(path, "a") as f:
        f.write('["not", "a", "ledger", "record"]\n')
        f.write('{"event": "stage_begin", "stage": "state_bui')  # torn write
    events, skipped = read_ledger(str(path))
    assert [e["event"] for e in events] == ["run_begin"]
    assert skipped == 2
    # A missing file reads as empty, never raises (the watchdog polls the
    # ledger before the child has written anything).
    assert read_ledger(str(tmp_path / "nope.jsonl")) == ([], 0)


def test_shared_t0_puts_processes_on_one_timeline(tmp_path):
    # A run spans several processes (watchdog parent, attempt children,
    # fallback continuation); passing the first writer's epoch keeps every
    # t_s on one timeline instead of restarting at 0 per process.
    import time

    path = tmp_path / "run.jsonl"
    parent = RunLedger(str(path), run_id="shared")
    child = RunLedger(str(path), run_id="shared", t0=parent.t0)
    assert child.t0 == parent.t0
    later = RunLedger(str(path), run_id="shared", t0=time.monotonic() - 100.0)
    later.emit(LedgerEvent.ATTEMPT_BEGIN, attempt=1)
    [event] = _events(path)
    assert event["t_s"] >= 100.0  # relative to the injected epoch
    parent.close()
    child.close()
    later.close()


def test_two_writers_share_one_file(tmp_path):
    # Parent watchdog + child workload append to the same ledger; the
    # merged stream stays line-parseable and correlated by run_id.
    path = tmp_path / "run.jsonl"
    parent = RunLedger(str(path), run_id="shared")
    child = RunLedger(str(path), run_id="shared")
    parent.emit(LedgerEvent.RUN_BEGIN)
    with child.stage("devices_init"):
        parent.emit(LedgerEvent.ATTEMPT_BEGIN, attempt=1)
    parent.emit(LedgerEvent.RUN_END, outcome="live")
    events, skipped = read_ledger(str(path))
    assert skipped == 0 and len(events) == 5
    assert {e["run_id"] for e in events} == {"shared"}
    parent.close()
    child.close()


def test_provenance_stamps_git_rev_and_code_hash(tmp_path):
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "a.py").write_text("x = 1\n")
    (tmp_path / "main.py").write_text("print('hi')\n")
    stamp = provenance(str(tmp_path), ("main.py", "pkg"))
    assert set(stamp) == {"git_rev", "code_hash", "hash_roots"}
    assert stamp["hash_roots"] == ["main.py", "pkg"]
    # Not a git repo: rev is None, hash still present.
    assert stamp["git_rev"] is None
    assert len(stamp["code_hash"]) == 16


def test_code_hash_tracks_content_not_noise(tmp_path):
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "a.py").write_text("x = 1\n")
    before = code_hash(str(tmp_path), ("pkg",))
    assert code_hash(str(tmp_path), ("pkg",)) == before  # deterministic
    # Caches and bytecode never stale a hash...
    (tmp_path / "pkg" / "__pycache__").mkdir()
    (tmp_path / "pkg" / "__pycache__" / "a.pyc").write_text("junk")
    assert code_hash(str(tmp_path), ("pkg",)) == before
    # ...a real source edit always does.
    (tmp_path / "pkg" / "a.py").write_text("x = 2\n")
    assert code_hash(str(tmp_path), ("pkg",)) != before


def test_every_stage_name_is_json_safe_and_lowercase():
    for name in STAGE_NAMES:
        assert name == name.lower() and " " not in name
        json.dumps({"stage": name})
    for event in LedgerEvent:
        assert event.value == event.value.lower()
