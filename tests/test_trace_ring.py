"""Device round-trace ring (ISSUE 17): the flight recorder INSIDE the engine.

The acceptance bar mirrors tests/test_telemetry_plane.py's, one refinement
deeper: a ``trace=R`` engine must be bit-identical — state, fault pytrees,
cut sequences, config-id chains, decision rounds, AND the telemetry lanes
themselves — to the ``trace=0`` telemetry engine on every driver spelling
(per-step, fused convergence, fleet wave, streaming pipeline). The ring is
write-only observation; perturbing the lanes it refines would be the same
bug as perturbing the protocol.

The ring's own contract (the decode pins ``engine_telemetry.trace_summary``
documents): the ring holds exactly the last ``min(R, total)`` rounds, the
wrap counter reconciles with the cursor AND with the telemetry plane's
``tl_rounds``, and the decode order is monotone across a wrap — the
``(epoch, round)`` stamps of the rotated window are strictly
lexicographically increasing, with contiguous global ``seq`` ordinals.

Budget (the PR-10 convention): every single-cluster test shares one
``trace=32`` program geometry so the jit cache amortizes the compiles; the
wrap test's tiny ``trace=6`` ring and the sharded/fleet/stream programs are
the only extra compile-bearing variants.
"""

import json
import sys
from pathlib import Path

import numpy as np
import pytest

import jax

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.serving import PoissonChurn, StreamDriver
from rapid_tpu.tenancy import TenantFleet
from rapid_tpu.utils.engine_telemetry import (
    TRACE_PATH_NAMES,
    TRACE_RECORD_FIELDS,
    first_divergent_round,
    zero_trace_summary,
)

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

#: The shared single-cluster ring capacity (one compile per driver kind).
R = 32


def _cluster(trace, n=24, n_slots=40, seed=0, **kw):
    vc = VirtualCluster.create(
        n, n_slots=n_slots, k=3, h=3, l=1, cohorts=2, fd_threshold=2,
        seed=seed, telemetry=True, trace=trace, **kw,
    )
    vc.assign_cohorts_roundrobin()
    return vc


def _trees_equal(a, b) -> bool:
    return bool(jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda x, y: bool((np.asarray(x) == np.asarray(y)).all()), a, b
    )))


def _host(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _churn_drive(vc, steps=10):
    """The test_telemetry_plane churn drive, verbatim: crash + join through
    the per-step seam, cut labels both sides observe identically."""
    cuts, ids, rounds = [], [], []
    joiners = np.nonzero(~np.asarray(vc.state.alive))[0][:2].tolist()
    vc.crash([3, 5])
    for i in range(steps):
        if i == 4:
            vc.inject_join_wave(joiners)
        was_alive = np.asarray(vc.state.alive)
        events = vc.step()
        if bool(events.decided):
            mask = np.asarray(events.winner_mask)
            cuts.append(frozenset(
                (s, "down" if was_alive[s] else "up")
                for s in np.nonzero(mask)[0].tolist()
            ))
            ids.append(vc.config_id)
            rounds.append(i)
    return cuts, ids, rounds


def _stamps(records):
    return [(r["epoch"], r["round"]) for r in records]


# ---------------------------------------------------------------------------
# Config gate: trace is a refinement of the telemetry plane
# ---------------------------------------------------------------------------


def test_trace_requires_telemetry_and_rejects_negative_capacity():
    with pytest.raises(ValueError, match="requires telemetry"):
        VirtualCluster.create(24, k=3, h=3, l=1, trace=4, telemetry=False)
    with pytest.raises(ValueError, match=">= 0"):
        VirtualCluster.create(24, k=3, h=3, l=1, trace=-1, telemetry=True)


# ---------------------------------------------------------------------------
# Bit-identity: trace=R vs trace=0, every driver spelling
# ---------------------------------------------------------------------------


def test_step_drive_bit_identical_trace_on_off():
    """The tier-1 representative: one crash+join churn drive, trace on vs
    off (both telemetry=1) — identical cuts, config-id chains, decision
    rounds, final state/fault pytrees, AND identical telemetry lanes (the
    ring must not perturb the plane it refines)."""
    off = _cluster(trace=0)
    on = _cluster(trace=R)
    expected = _churn_drive(off)
    got = _churn_drive(on)
    assert expected[0], "drive produced no cuts — the differential is vacuous"
    assert got == expected
    assert _trees_equal(on.state, off.state)
    assert _trees_equal(on.faults, off.faults)
    assert _trees_equal(_host(on.telem), _host(off.telem))
    assert on.config_id == off.config_id

    on.sync()
    trace = on.trace
    assert trace["capacity"] == R
    assert trace["rounds_recorded"] == 10 == on.activity["rounds"]
    assert trace["wraps"] == 0
    assert trace["rounds_held"] == 10
    assert [r["seq"] for r in trace["records"]] == list(range(10))
    assert trace["decisions_held"] == len(expected[0])
    decided = [r for r in trace["records"] if r["path"]]
    # The ring's decision records name the SAME rounds the host drive saw
    # decide, with a registered path code.
    assert [r["seq"] for r in decided] == expected[2]
    assert all(r["path"] in TRACE_PATH_NAMES for r in decided)
    assert off.trace is None  # trace=0: no ring, no fetch, ever


def test_fused_drivers_bit_identical_and_ring_path_independent():
    """``run_to_decision``/``run_until_membership`` with the ring riding
    the while-loop carry: identical resolution to trace=0, and the ring a
    fused drive accumulates equals a per-step drive's ring raw leaf for
    raw leaf (the while-loop body IS the step body)."""
    off = _cluster(trace=0, seed=1)
    on = _cluster(trace=R, seed=1)
    stepped = _cluster(trace=R, seed=1)
    off.crash([2, 7]); on.crash([2, 7]); stepped.crash([2, 7])

    expected = off.run_to_decision(max_steps=32)
    got = on.run_to_decision(max_steps=32)
    assert got[0] == expected[0] and got[1] == expected[1]
    assert got[3] == expected[3]
    np.testing.assert_array_equal(np.asarray(got[2]), np.asarray(expected[2]))
    assert _trees_equal(on.state, off.state)
    assert _trees_equal(_host(on.telem), _host(off.telem))

    for _ in range(got[0]):
        stepped.step()
    assert _trees_equal(_host(on.trace_ring), _host(stepped.trace_ring))

    # The multi-cut wave: same resolution, same config chain, on vs off.
    off2 = _cluster(trace=0, seed=2)
    on2 = _cluster(trace=R, seed=2)
    for vc in (off2, on2):
        vc.crash([1, 4, 9])
    expected2 = off2.run_until_membership(21, max_steps=64, min_cuts=1)
    got2 = on2.run_until_membership(21, max_steps=64, min_cuts=1)
    assert got2 == expected2
    assert _trees_equal(on2.state, off2.state)
    assert on2.config_id == off2.config_id
    on2.sync()
    assert on2.trace["rounds_recorded"] == on2.activity["rounds"]


# ---------------------------------------------------------------------------
# The ring contract: last min(R, total), wrap reconciliation, monotone decode
# ---------------------------------------------------------------------------


def test_ring_holds_exactly_last_min_R_total_and_decode_is_monotone():
    """The wraparound property, pinned against an unwrapped reference twin:
    a trace=6 ring driven 17 rounds holds exactly the LAST 6 records a
    trace=32 twin of the same drive recorded, field for field; the wrap
    counter reconciles with the cursor (``wraps == cursor // R``) and the
    cursor with the telemetry plane (``cursor == tl_rounds``); the decoded
    ``(epoch, round)`` stamps stay strictly increasing across the wrap."""
    small, big = _cluster(trace=6, seed=3), _cluster(trace=R, seed=3)
    joiners = np.nonzero(~np.asarray(small.state.alive))[0][:2].tolist()

    # Pre-wrap boundary: the ring is just the prefix.
    for vc in (small, big):
        vc.crash([3, 5])
        for _ in range(4):
            vc.step()
        vc.sync()
    pre = small.trace
    assert (pre["rounds_recorded"], pre["rounds_held"], pre["wraps"]) == (4, 4, 0)
    assert [r["seq"] for r in pre["records"]] == [0, 1, 2, 3]
    assert pre["records"] == big.trace["records"]

    # Drive past two wraps (17 records through a 6-slot ring).
    for vc in (small, big):
        vc.inject_join_wave(joiners)
        for _ in range(13):
            vc.step()
        vc.sync()
    trace, ref = small.trace, big.trace
    total = 17
    assert trace["rounds_recorded"] == total == small.activity["rounds"]
    assert trace["rounds_held"] == min(6, total) == 6
    assert trace["wraps"] == total // 6 == 2
    # Exactly the last 6 rounds ever recorded, bit for bit — nothing
    # phantom, nothing stale from before the wrap.
    assert trace["records"] == ref["records"][-6:]
    assert [r["seq"] for r in trace["records"]] == list(range(total - 6, total))
    stamps = _stamps(trace["records"])
    assert stamps == sorted(stamps) and len(set(stamps)) == len(stamps)
    # The unwrapped twin held everything and agrees on the reconciliation.
    assert ref["rounds_held"] == total and ref["wraps"] == 0
    ref_stamps = _stamps(ref["records"])
    assert ref_stamps == sorted(ref_stamps) and len(set(ref_stamps)) == total
    # Two decodes of overlapping windows of the SAME history never fork.
    assert first_divergent_round(trace, ref) is None


def test_zero_minted_attach_reads_an_empty_ring():
    """The never-mint-a-series-mid-run rule: a fresh trace=R engine reads a
    fully-formed all-zero summary (capacity, no records) BEFORE any sync —
    and its telemetry snapshot carries the section from the first frame."""
    vc = _cluster(trace=R, seed=4)
    assert vc.trace == zero_trace_summary(R)
    assert vc.trace["capacity"] == R and vc.trace["records"] == []
    snap = vc.telemetry_snapshot()
    assert snap["engine"]["trace"]["rounds_recorded"] == 0
    # The accessor copies: mutating a read never corrupts the cache.
    vc.trace["records"].append("garbage")
    assert vc.trace["records"] == []


# ---------------------------------------------------------------------------
# Fleet: tenant rings coast-gate exactly like the lanes they refine
# ---------------------------------------------------------------------------


def _fleet(trace, b=3, n=16, seed0=10):
    clusters = []
    for i in range(b):
        vc = VirtualCluster.create(
            n, k=3, h=3, l=1, cohorts=2, fd_threshold=2, seed=seed0 + i,
            telemetry=True, trace=trace,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash(list(range(1, 2 + i)))  # tenants resolve at different rounds
        clusters.append(vc)
    return clusters


def test_fleet_wave_rings_bit_identical_to_per_cluster_drives():
    """The coast-gating pin at ring grain: tenants resolving at different
    rounds coast with FROZEN rings — each tenant's ring equals its own
    per-cluster drive's ring, record for record — and the traced wave's
    results match the trace=0 wave."""
    singles = _fleet(trace=R)
    targets = [vc.membership_size - (1 + i) for i, vc in enumerate(singles)]
    expected = [
        vc.run_until_membership(t, max_steps=64, min_cuts=1)
        for vc, t in zip(singles, targets)
    ]
    assert all(r[2] for r in expected), "a tenant failed to resolve"

    fleet = TenantFleet.from_clusters(_fleet(trace=R))
    rounds, cuts, resolved, _ = fleet.run_until_membership(
        np.asarray(targets), max_steps=64, min_cuts=1
    )
    assert resolved.all()
    assert rounds.tolist() == [r[0] for r in expected]
    assert cuts.tolist() == [r[1] for r in expected]
    fleet.sync()
    tenant_trace = fleet.tenant_trace
    for t, vc in enumerate(singles):
        tenant_ring = jax.tree_util.tree_map(
            lambda x, t=t: np.asarray(x)[t], fleet.trace_ring
        )
        assert _trees_equal(tenant_ring, _host(vc.trace_ring)), t
        vc.sync()
        assert tenant_trace[t] == vc.trace, t

    # Same wave, trace off: the fleet results are unchanged.
    off = TenantFleet.from_clusters(_fleet(trace=0))
    rounds0, cuts0, resolved0, _ = off.run_until_membership(
        np.asarray(targets), max_steps=64, min_cuts=1
    )
    assert resolved0.all()
    assert rounds0.tolist() == rounds.tolist()
    assert cuts0.tolist() == cuts.tolist()
    assert _trees_equal(off.state, fleet.state)
    assert _trees_equal(_host(off.telem), _host(fleet.telem))
    assert off.tenant_trace is None


# ---------------------------------------------------------------------------
# Stream: the drain boundary decodes the ring and attributes waves
# ---------------------------------------------------------------------------


def test_stream_drive_bit_identical_and_drain_attributes_waves():
    """The pipelined driver over a traced target: bit-identical cuts/state
    to the trace=0 stream, zero extra fetches before the drain, and the
    drain's ring decomposition attributes every submitted wave (none
    evicted at this depth) with decision offsets inside the wave span."""
    waves = PoissonChurn(24, 40, rate=1.0, seed=7).waves(6)

    on = _cluster(trace=R, seed=0)
    driver_on = StreamDriver(on, rounds_per_wave=4, depth=2)
    for wave in waves:
        driver_on.submit(wave)
    result_on = driver_on.drain()

    off = _cluster(trace=0, seed=0)
    driver_off = StreamDriver(off, rounds_per_wave=4, depth=2)
    for wave in waves:
        driver_off.submit(wave)
    result_off = driver_off.drain()

    assert result_on.cuts == result_off.cuts
    assert result_on.waves == result_off.waves == 6
    assert _trees_equal(on.state, off.state)
    assert _trees_equal(on.faults, off.faults)
    assert on.config_id == off.config_id

    assert on.trace["rounds_recorded"] == result_on.rounds == 24
    tj = driver_on.last_trajectory
    assert tj is not None
    assert driver_off.last_trajectory is None  # trace=0: no ring to decompose
    assert tj["rounds_per_wave"] == 4
    assert tj["waves_attributed"] + tj["waves_evicted"] == 6
    assert tj["waves_evicted"] == 0  # R=32 holds all 24 streamed rounds
    assert tj["decided_waves"] + tj["undecided_waves"] == 6
    assert tj["decided_waves"] >= 1
    assert 1 <= tj["rounds_to_decision_p50"] <= 4
    assert 1 <= tj["rounds_to_decision_max"] <= 4


# ---------------------------------------------------------------------------
# Sharded: the mesh twin and the fleet placement rules
# ---------------------------------------------------------------------------


def test_sharded_step_trace_bit_identical_and_fleet_rings_shard():
    """The ring under a real device mesh: ``make_sharded_step_trace``
    matches the single-device per-step drive bit for bit — state, lanes,
    AND ring — and tenant-stacked rings place onto the 3-D fleet mesh
    through the same rule table (``fleet_trace_shardings``: leading
    'tenant' axis, lane dims replicated, values unchanged)."""
    from rapid_tpu.parallel.mesh import (
        TENANT_AXIS,
        fleet_trace_shardings,
        make_mesh,
        make_sharded_step_trace,
        shard_faults,
        shard_pytree,
        shard_state,
        telemetry_shardings,
        trace_shardings,
    )

    single = _cluster(trace=R, seed=6)
    single.crash([2, 7])
    for _ in range(8):
        single.step()

    vc = _cluster(trace=R, seed=6)
    vc.crash([2, 7])
    mesh = make_mesh(jax.devices()[:8])
    step = make_sharded_step_trace(vc.cfg, mesh)
    state = shard_state(vc.state, mesh)
    telem = shard_pytree(vc.telem, telemetry_shardings(mesh), mesh=mesh)
    ring = shard_pytree(vc.trace_ring, trace_shardings(mesh), mesh=mesh)
    faults = shard_faults(vc.faults, mesh)
    for _ in range(8):
        state, telem, ring, _events = step(state, telem, ring, faults)
    assert _trees_equal(state, single.state)
    assert _trees_equal(_host(telem), _host(single.telem))
    assert _trees_equal(_host(ring), _host(single.trace_ring))
    single.sync()
    assert single.trace["rounds_recorded"] == 8

    fleet = TenantFleet.from_clusters(_fleet(trace=R, b=4))
    shardings = fleet_trace_shardings(
        make_mesh(jax.devices()[:8], shape=(2, 2, 2))
    )
    for leaf in jax.tree_util.tree_leaves(shardings):
        assert leaf.spec and leaf.spec[0] == TENANT_AXIS
    placed = shard_pytree(
        fleet.trace_ring, shardings,
        mesh=make_mesh(jax.devices()[:8], shape=(2, 2, 2)),
    )
    assert _trees_equal(_host(placed), _host(fleet.trace_ring))


# ---------------------------------------------------------------------------
# Host decode instruments: divergence naming, timeline merge, dashboard pane
# ---------------------------------------------------------------------------


def test_first_divergent_round_names_the_first_forked_record():
    vc = _cluster(trace=R, seed=8)
    vc.crash([2, 7])
    vc.run_to_decision(max_steps=32)
    vc.sync()
    a = vc.trace
    assert a["records"], "drive recorded nothing — the fork test is vacuous"
    assert first_divergent_round(a, a) is None

    # A tampered field forks at exactly that record's global ordinal.
    b = dict(a)
    b["records"] = [dict(r) for r in a["records"]]
    victim = len(b["records"]) // 2
    b["records"][victim]["active"] += 1
    assert first_divergent_round(a, b) == a["records"][victim]["seq"]

    # A truncated history forks at the first round the shorter run never
    # executed, even where the overlapping records agree.
    c = dict(a)
    c["records"] = [dict(r) for r in a["records"][:-1]]
    c["rounds_recorded"] = a["rounds_recorded"] - 1
    assert first_divergent_round(a, c) == c["rounds_recorded"]


def test_traceview_merges_the_engine_lane_from_a_trace_artifact(tmp_path):
    """The flight-recorder join: a repro directory's ``trace.json`` becomes
    the synthetic ``(engine)`` lane — one registered ``engine_round`` event
    per held record, decisions and conflicts interleaved — through THE
    shared loader (``scenario_snapshots``), ordered by global ``seq``."""
    import traceview

    vc = _cluster(trace=R, seed=9)
    vc.crash([2, 7])
    vc.run_to_decision(max_steps=32)
    vc.sync()
    summary = vc.trace
    (tmp_path / "trace.json").write_text(json.dumps(summary))
    (tmp_path / "schedule.json").write_text("{}")  # metadata, never a snapshot

    snapshots = traceview.scenario_snapshots(tmp_path)
    assert [s["node"] for s in snapshots] == [traceview.ENGINE_LANE]
    events = traceview.merge_events(snapshots)
    rounds = [e for e in events if e["name"] == "engine_round"]
    assert [e["fields"]["seq"] for e in rounds] == [
        r["seq"] for r in summary["records"]
    ]
    assert len([e for e in events if e["name"] == "engine_decision"]) == (
        summary["decisions_held"]
    )
    # Pre-trace directories contribute no engine lane and never crash.
    assert traceview.engine_trace_snapshot(tmp_path / "absent.json") is None
    # A torn artifact is a load error, not a silent empty lane.
    (tmp_path / "trace.json").write_text("{\"no\": \"records\"}")
    with pytest.raises(traceview.SnapshotLoadError):
        traceview.engine_trace_snapshot(tmp_path / "trace.json")


def test_device_ring_cross_validates_host_recorder_on_differential_scenario(
    tmp_path,
):
    """The acceptance differential: ONE fault schedule through the host
    protocol stack (per-node flight recorders) and through a traced engine
    replay (the ``replay_through_engine`` matched-parameter construction +
    the shared ``inject_engine_event`` mapping). The host cut sequence must
    refine the engine's (the established differential oracle), the ring's
    round-indexed decision sequence must carry exactly the engine's
    decisions, and traceview must render one merged host + ``(chaos)`` +
    ``(engine)`` timeline from the REAL artifact directory."""
    import traceview

    from rapid_tpu.sim import fuzz as simfuzz
    from rapid_tpu.sim.oracles import cuts_refine, inject_engine_event
    from rapid_tpu.types import EdgeStatus

    schedule = simfuzz.scenario_family("crash_during_join", 7)
    result = simfuzz.run_schedule(schedule)
    assert result.final_converged and result.cuts

    vc = VirtualCluster.from_endpoints(
        list(result.endpoints), n_slots=len(result.endpoints),
        n_members=schedule.n0, k=10, h=9, l=4, fd_threshold=1,
        delivery_spread=0, telemetry=True, trace=256,
    )
    expected_members = schedule.n0
    engine_groups = []
    for group in schedule.membership_phases():
        for event in group:
            expected_members += inject_engine_event(vc, event)
        cuts = []
        for _ in range(len(group) + 1):
            was_alive = np.asarray(vc.state.alive)
            _rounds, decided, winner, n_members = vc.run_to_decision(
                max_steps=48
            )
            assert decided, f"engine did not decide for {group}"
            mask = np.asarray(winner)
            cuts.append(frozenset(
                (
                    result.endpoints[s],
                    EdgeStatus.DOWN if was_alive[s] else EdgeStatus.UP,
                )
                for s in np.nonzero(mask)[0].tolist()
            ))
            if n_members == expected_members:
                break
        else:
            raise AssertionError(f"{group} never reached {expected_members}")
        engine_groups.append(cuts)
    assert cuts_refine(result.cuts, engine_groups) is None

    # Same cuts => same round-indexed decision sequence: the ring (sized to
    # hold the whole replay) records one decided round per engine cut, in
    # decode order, each with a registered path code.
    vc.sync()
    ring = vc.trace
    assert ring["rounds_held"] == ring["rounds_recorded"]  # nothing wrapped
    decided_records = [r for r in ring["records"] if r["path"]]
    assert len(decided_records) == sum(len(g) for g in engine_groups)
    assert ring["decisions_held"] == len(decided_records)
    assert all(r["path"] in TRACE_PATH_NAMES for r in decided_records)
    # The host split at most refines engine cuts, never the reverse.
    assert len(result.cuts) >= len(decided_records)

    # The merged timeline from the real artifact dir: host node lanes, the
    # fault-injection lane, AND the device engine lane in one ordering.
    artifacts = tmp_path / "repro"
    simfuzz.write_repro(result, [], artifacts)
    (artifacts / "trace.json").write_text(json.dumps(ring))
    snapshots = traceview.scenario_snapshots(artifacts)
    nodes = {s["node"] for s in snapshots}
    assert traceview.ENGINE_LANE in nodes
    assert traceview.FAULT_LANE in nodes
    assert len(nodes) >= 2 + schedule.n0  # every host node has a lane
    events = traceview.merge_events(snapshots)
    names = {e["name"] for e in events}
    assert "engine_round" in names and "engine_decision" in names
    assert "view_change" in names  # the host recorder's commit events
    engine_decisions = [
        e for e in events if e["name"] == "engine_decision"
    ]
    # The decision events carry the ring's global round ordinal (the
    # recorder's own seq is its per-node event counter, not the round).
    assert [e["fields"]["seq"] for e in engine_decisions] == [
        r["seq"] for r in decided_records
    ]


def test_clustertop_rounds_pane_renders_and_tolerates_torn_snapshots():
    """The ROUNDS pane: one row per decoded ring (cluster label, fleet
    ``node/t<i>`` lanes), dashes for torn records, nothing at all for
    pre-trace snapshots."""
    import clustertop

    vc = _cluster(trace=R, seed=9)
    vc.crash([2, 7])
    vc.run_to_decision(max_steps=32)
    vc.sync()
    snap = vc.telemetry_snapshot()
    snap["node"] = "engine0"
    torn = {"node": "torn", "engine": {
        "trace": {"records": "garbage", "rounds_recorded": None}
    }}
    lines = clustertop.render_rounds_pane([snap, torn, {"node": "old", "engine": {}}])
    assert lines and "ROUNDS" in lines[1]
    body = "\n".join(lines)
    assert "engine0" in body and "torn" in body and "old" not in body
    engine_row = next(l for l in lines if l.startswith("engine0"))
    trace = snap["engine"]["trace"]
    assert str(trace["rounds_recorded"]) in engine_row
    assert TRACE_PATH_NAMES[trace["last_path"]] in engine_row
    torn_row = next(l for l in lines if l.startswith("torn"))
    assert set(torn_row.split()[1:]) == {"-"}
    # No traced snapshot at all: the pane vanishes rather than render empty.
    assert clustertop.render_rounds_pane([{"node": "old", "engine": {}}]) == []
    # The record fields the pane's sparkline walks are the frozen decode
    # vocabulary — a renamed lane breaks here, not silently in a terminal.
    assert all(
        set(TRACE_RECORD_FIELDS) <= set(r) for r in trace["records"]
    )
