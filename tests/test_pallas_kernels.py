"""Bitmask watermark core + Mosaic delivery kernel.

The watermark merge/classify is checked against the dense-matrix semantics
(it is plain jnp — the one-time Mosaic version measured slower than XLA's
fusion and was deleted). The DELIVERY kernel — the Mosaic path the engine
actually ships — is checked bit-identical to the engine's jnp path in
interpret mode on CPU and as real Mosaic on TPU."""

import numpy as np
import jax.numpy as jnp
import pytest

from rapid_tpu.ops.pallas_kernels import (
    bits_to_reports_matrix,
    reports_matrix_to_bits,
    watermark_merge_classify,
)

H, L, K = 8, 3, 10


def dense_reference(old_bits, new_bits, subject_mask):
    old = bits_to_reports_matrix(jnp.asarray(old_bits), K)
    new = bits_to_reports_matrix(jnp.asarray(new_bits), K)
    merged = (np.asarray(old) | np.asarray(new)) & np.asarray(subject_mask)[:, None]
    tally = merged.sum(axis=1)
    cls = np.where(tally >= H, 2, np.where((tally >= L) & (tally < H), 1, 0))
    return merged, cls


def test_roundtrip_bits_matrix():
    rng = np.random.default_rng(0)
    reports = rng.random((4, 256, K)) < 0.3
    bits = reports_matrix_to_bits(jnp.asarray(reports))
    back = bits_to_reports_matrix(bits, K)
    np.testing.assert_array_equal(np.asarray(back), reports)


def test_watermark_classify_matches_dense():
    rng = np.random.default_rng(1)
    n = 2048
    old = rng.integers(0, 1 << K, size=n, dtype=np.uint32)
    new = rng.integers(0, 1 << K, size=n, dtype=np.uint32)
    mask = rng.random(n) < 0.9
    merged_bits, cls = watermark_merge_classify(
        jnp.asarray(old), jnp.asarray(new), jnp.asarray(mask), H, L
    )
    dense_merged, dense_cls = dense_reference(old, new, mask)
    np.testing.assert_array_equal(
        np.asarray(bits_to_reports_matrix(merged_bits, K)), dense_merged
    )
    np.testing.assert_array_equal(np.asarray(cls), dense_cls)


def test_watermark_boundaries():
    # Exactly L-1, L, H-1, H reports.
    cases = {0: 0, L - 1: 0, L: 1, H - 1: 1, H: 2, K: 2}
    n = 1024
    bits = np.zeros(n, dtype=np.uint32)
    expected = np.zeros(n, dtype=np.int32)
    for i, (count, cls) in enumerate(cases.items()):
        bits[i] = (1 << count) - 1
        expected[i] = cls
    _, cls = watermark_merge_classify(
        jnp.asarray(bits), jnp.zeros(n, dtype=jnp.uint32), jnp.ones(n, dtype=bool), H, L
    )
    np.testing.assert_array_equal(np.asarray(cls)[: len(cases)], expected[: len(cases)])


@pytest.mark.parametrize("c,spread,permille,lanes", [
    (2, 0, 1000, 128),     # no jitter
    (32, 1, 1000, 128),    # one cohort word, legacy uniform draw
    (64, 2, 1000, 128),    # two words
    # The two largest grids ride the unfiltered check.sh pass (~21 s wall
    # combined); the word-boundary/sub-round/wide-lane properties they add
    # stay covered at the smaller shapes above and below.
    pytest.param(96, 3, 300, 128, marks=pytest.mark.slow),
    pytest.param(33, 1, 250, 128, marks=pytest.mark.slow),
    (64, 2, 1000, 256),    # wide lane tile: bit-identical across widths
    (8, 2, 1000, 512),     # the 1M-point cohort shape, wider still
])
def test_delivery_kernel_matches_engine_jnp_path(c, spread, permille, lanes):
    # The fused delivery kernel (interpret mode off-TPU, real Mosaic on
    # device) must be bit-identical to the ENGINE's live jnp path — same
    # function, same state — so any drift in either side fails here, not
    # only in the on-TPU smoke. Real cluster state: crashed members, an
    # rx-blocked cohort, edges at several ages mid-convergence.
    import jax

    from rapid_tpu.models.virtual_cluster import (
        VirtualCluster,
        _deliver_alerts,
        _edge_masks,
    )
    from rapid_tpu.ops.pallas_kernels import delivery_new_bits_pallas

    rng = np.random.default_rng(c * 1000 + spread)
    n = 1000  # ragged vs the 128-lane tile
    vc = VirtualCluster.create(
        n, cohorts=c, k=K, fd_threshold=1, seed=c, delivery_spread=spread,
        delivery_prob_permille=permille,
    )
    vc.assign_cohorts_roundrobin()
    rx_block = np.zeros((c, vc.cfg.n), dtype=bool)
    rx_block[c - 1] = rng.random(vc.cfg.n) < 0.3  # last cohort partly deaf
    vc.set_rx_block(rx_block)
    vc.crash(rng.choice(n, size=20, replace=False))
    vc.stagger_fd_counts(np.random.default_rng(1), spread_rounds=2)
    for _ in range(3):  # edges now at several distinct fire ages
        vc.step()

    cfg, state = vc.cfg, vc.state
    _, blocked_rows = _edge_masks(cfg, state, vc.faults)
    want = _deliver_alerts(cfg, state, state.fire_round, blocked_rows)
    age_kn = state.round_idx - state.fire_round.T
    got = delivery_new_bits_pallas(
        blocked_rows,
        age_kn,
        state.config_epoch.astype(jnp.uint32).reshape(1),
        K,
        spread,
        permille,
        interpret=jax.default_backend() != "tpu",
        lanes=lanes,
    )[:c]
    assert np.asarray(want).any() or spread == 0  # scenario actually delivers
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_profiling_trace_captures_convergence(tmp_path):
    # Exercise utils/profiling end-to-end: trace a real (tiny) convergence
    # and assert a TensorBoard-compatible trace landed on disk.
    # Rides the unfiltered check.sh pass (~33 s wall: the profiler wraps a
    # full compile); tests/test_profiling.py keeps every utils/profiling
    # seam (no-op fallback, nested rejection, failed stop) in tier-1.
    from rapid_tpu.models.virtual_cluster import VirtualCluster
    from rapid_tpu.utils.profiling import annotate, trace

    vc = VirtualCluster.create(48, fd_threshold=2, seed=0)
    vc.crash([5])
    with trace(str(tmp_path)):
        with annotate("convergence"):
            rounds, decided, _, _ = vc.run_to_decision(max_steps=32)
    assert decided
    traced = list(tmp_path.rglob("*.trace.json.gz")) + list(tmp_path.rglob("*.xplane.pb"))
    assert traced, f"no trace files under {tmp_path}"
