"""utils/profiling hardening: graceful no-op where jax.profiler is missing
or refuses to start, eager rejection of nested trace() blocks, and no-op
annotate spans. (The happy path — a real trace landing on disk around a
real convergence — is covered by tests/test_pallas_kernels.py.)
"""

import logging

import pytest

from rapid_tpu.utils import profiling


@pytest.mark.slow
def test_nested_trace_is_rejected_eagerly(tmp_path):
    # Rides the unfiltered check.sh pass (~16 s wall: three REAL
    # jax.profiler trace starts). Tier-1 representative of the guard:
    # test_guard_resets_when_block_raises (one trace start, same
    # already-active latch).
    with profiling.trace(str(tmp_path / "outer")):
        with pytest.raises(RuntimeError, match="does not nest"):
            with profiling.trace(str(tmp_path / "inner")):
                pass  # pragma: no cover — must not be reached
    # The guard resets after exit: a fresh trace works again.
    with profiling.trace(str(tmp_path / "again")):
        pass


def test_guard_resets_when_block_raises(tmp_path):
    with pytest.raises(ValueError, match="inner failure"):
        with profiling.trace(str(tmp_path / "t")):
            raise ValueError("inner failure")
    with profiling.trace(str(tmp_path / "t2")):
        pass  # not "already active"


def test_noop_when_profiler_unavailable(tmp_path, monkeypatch, caplog):
    monkeypatch.setattr(profiling, "profiler_available", lambda: False)
    ran = []
    with caplog.at_level(logging.WARNING, logger="rapid_tpu.utils.profiling"):
        with profiling.trace(str(tmp_path)):
            ran.append(True)
    assert ran  # the block still executed
    assert any("unavailable" in r.message for r in caplog.records)
    # annotate degrades to a no-op context manager.
    with profiling.annotate("phase"):
        ran.append(True)
    assert len(ran) == 2


def test_noop_when_start_trace_raises(tmp_path, monkeypatch, caplog):
    import jax

    def boom(log_dir):
        raise RuntimeError("backend has no profiler")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    stopped = []
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stopped.append(True))
    ran = []
    with caplog.at_level(logging.WARNING, logger="rapid_tpu.utils.profiling"):
        with profiling.trace(str(tmp_path)):
            ran.append(True)
    assert ran
    assert any("running unprofiled" in r.message for r in caplog.records)
    assert not stopped  # never started -> never stopped


def test_failed_stop_does_not_mask_block_result(tmp_path, monkeypatch, caplog):
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda log_dir: None)

    def bad_stop():
        raise RuntimeError("flush failed")

    monkeypatch.setattr(jax.profiler, "stop_trace", bad_stop)
    with caplog.at_level(logging.WARNING, logger="rapid_tpu.utils.profiling"):
        with profiling.trace(str(tmp_path)):
            pass  # block succeeds; the failed stop must not raise
    assert any("stop_trace" in r.message for r in caplog.records)


def test_profiler_available_reports_bool():
    assert isinstance(profiling.profiler_available(), bool)
