"""Virtual-cluster engine tests: protocol outcomes at N in the hundreds,
mirroring the cluster-level scenarios on the device path."""

import numpy as np
import pytest

from rapid_tpu.models.virtual_cluster import VirtualCluster


def test_single_crash_converges():
    vc = VirtualCluster.create(100, k=10, h=9, l=4, fd_threshold=3, seed=0)
    assert vc.membership_size == 100
    config_before = vc.config_id
    vc.crash([17])
    rounds, events = vc.run_until_converged()
    assert events is not None
    assert vc.membership_size == 99
    assert not vc.alive_mask[17]
    assert vc.config_epoch == 1
    assert vc.config_id != config_before
    # FD threshold of 3 ticks plus one round to tally/decide.
    assert rounds >= 3


def test_concurrent_crashes_single_cut():
    vc = VirtualCluster.create(200, fd_threshold=3, seed=1)
    victims = [5, 50, 120, 199]
    vc.crash(victims)
    rounds, events = vc.run_until_converged()
    assert events is not None
    # All four removed in ONE consensus decision (the multi-node cut).
    assert vc.config_epoch == 1
    assert vc.membership_size == 196
    winner = np.asarray(events.winner_mask)
    assert set(np.nonzero(winner)[0].tolist()) == set(victims)


def test_one_percent_crash_fault():
    n = 1000
    vc = VirtualCluster.create(n, fd_threshold=3, seed=2)
    rng = np.random.default_rng(0)
    victims = rng.choice(n, size=10, replace=False)
    vc.crash(victims)
    vc.run_until_converged()
    assert vc.membership_size == n - 10
    assert not vc.alive_mask[victims].any()


def test_join_wave():
    vc = VirtualCluster.create(100, n_slots=164, fd_threshold=3, seed=3)
    joiners = list(range(100, 164))
    vc.inject_join_wave(joiners)
    rounds, events = vc.run_until_converged()
    assert events is not None
    assert vc.membership_size == 164
    assert vc.alive_mask[joiners].all()
    assert vc.config_epoch == 1


def test_join_then_crash_two_cuts():
    # Joiners arrive with full gatekeeper reports and propose immediately;
    # crashes surface only after fd_threshold probe windows — two separate
    # consensus rounds, like the reference's per-configuration proposals.
    vc = VirtualCluster.create(50, n_slots=60, fd_threshold=3, seed=4)
    vc.crash([7, 23])
    vc.inject_join_wave(list(range(50, 60)))
    rounds, events = vc.run_until_converged()
    assert events is not None
    assert vc.config_epoch == 1
    assert vc.membership_size == 60  # joiners admitted first
    assert vc.alive_mask[50:60].all()
    rounds, events = vc.run_until_converged()
    assert events is not None
    assert vc.config_epoch == 2
    assert vc.membership_size == 58
    assert not vc.alive_mask[[7, 23]].any()


def test_sequential_view_changes():
    vc = VirtualCluster.create(80, fd_threshold=3, seed=5)
    vc.crash([3])
    vc.run_until_converged()
    assert vc.membership_size == 79
    first_epoch_config = vc.config_id
    vc.crash([42])
    vc.run_until_converged()
    assert vc.membership_size == 78
    assert vc.config_epoch == 2
    assert vc.config_id != first_epoch_config


def test_device_loop_matches_host_loop():
    # run_to_decision (single-dispatch lax.while_loop) must land on the same
    # outcome as the per-round host loop.
    a = VirtualCluster.create(150, fd_threshold=3, seed=9)
    b = VirtualCluster.create(150, fd_threshold=3, seed=9)
    victims = [10, 99]
    a.crash(victims)
    b.crash(victims)
    rounds_host, events = a.run_until_converged()
    rounds_dev, decided, winner, members_dev = b.run_to_decision()
    assert decided
    assert rounds_dev == rounds_host
    assert members_dev == a.membership_size  # packed-fetch membership agrees
    np.testing.assert_array_equal(a.alive_mask, b.alive_mask)
    assert set(np.nonzero(winner)[0].tolist()) == set(victims)
    assert int(b.state.config_hi) == int(a.state.config_hi)


def test_device_loop_no_decision_hits_max_steps():
    vc = VirtualCluster.create(64, seed=10)
    rounds, decided, winner, _ = vc.run_to_decision(max_steps=5)
    assert rounds == 5 and not decided
    assert not winner.any()


def test_no_faults_no_decision():
    vc = VirtualCluster.create(64, seed=6)
    for _ in range(8):
        events = vc.step()
        assert not bool(events.decided)
        assert int(events.alerts_emitted) == 0
    assert vc.membership_size == 64
    assert vc.config_epoch == 0


def test_flaky_below_l_does_not_converge():
    # A single flaky edge (below L distinct rings) must never produce a cut:
    # stability against sub-L gossip, the almost-everywhere agreement
    # precondition.
    vc = VirtualCluster.create(60, k=10, h=9, l=4, fd_threshold=2, seed=7)
    probe_fail = np.zeros((vc.cfg.n, vc.cfg.k), dtype=bool)
    probe_fail[11, :2] = True  # 2 < L rings report subject 11
    vc.set_flaky_edges(probe_fail)
    for _ in range(12):
        events = vc.step()
        assert not bool(events.decided)
    assert vc.membership_size == 60


def test_flip_flop_partition_removes_exactly_faulty_set():
    # BASELINE config 4 / paper Fig. 9: one-way partitions that flip on and
    # off. Rapid's watermarks + FD hysteresis must remove exactly the faulty
    # set; healthy members must never be evicted (the reference's comparison
    # systems oscillate forever here).
    n = 400
    vc = VirtualCluster.create(n, k=10, h=9, l=4, fd_threshold=4, seed=12)
    faulty = list(range(40, 50))
    on_mask = np.zeros((vc.cfg.n, vc.cfg.k), dtype=bool)
    on_mask[faulty, :] = True
    off_mask = np.zeros_like(on_mask)

    removed_healthy = False
    for cycle in range(6):
        vc.set_flaky_edges(on_mask if cycle % 2 == 0 else off_mask)
        for _ in range(3):
            vc.step()
        alive = vc.alive_mask
        removed_healthy |= (~alive[: 40]).any() or (~alive[50:n]).any()
    # Keep partitions on until convergence completes.
    vc.set_flaky_edges(on_mask)
    vc.run_until_converged(max_steps=32)
    alive = vc.alive_mask
    assert not removed_healthy
    assert not alive[faulty].any(), "faulty set fully removed"
    assert alive[:40].all() and alive[50:n].all(), "no healthy member evicted"
    assert vc.membership_size == n - len(faulty)


def test_contested_round_fallback_picks_plurality():
    # Two cohorts announce genuinely different cuts: cohort 1 never hears
    # about the second victim (its observers are rx-blocked), so it proposes
    # a subset. The fast round can't reach N-F identical votes; the modeled
    # classic fallback must commit the plurality proposal everywhere.
    n = 120
    vc = VirtualCluster.create(n, fd_threshold=2, seed=11)
    cohort_of = np.zeros(n, dtype=np.int32)
    cohort_of[80:] = 1  # minority cohort
    vc.assign_cohorts(cohort_of)
    v1, v2 = 10, 60
    vc.crash([v1, v2])
    # Cohort 1 cannot hear from ANY observer of v2 (block every slot except
    # v2's own): it will only ever tally v1.
    rx = np.zeros((vc.cfg.c, vc.cfg.n), dtype=bool)
    obs_of_v2 = np.asarray(vc.state.obs_idx)[:, v2]
    rx[1, obs_of_v2] = True
    vc.set_rx_block(rx)
    rounds, events = vc.run_until_converged(max_steps=64)
    assert events is not None
    winner = set(np.nonzero(np.asarray(events.winner_mask))[0].tolist())
    # Majority cohort's cut (both victims) wins; minority's subset loses.
    assert winner == {v1, v2}
    assert vc.membership_size == n - 2
    # The decision required the fallback (dissent makes N-F unreachable).
    assert int(events.total_votes) > int(events.max_votes)


def test_join_alerts_respect_delivery_masks():
    # A cohort that cannot hear a joiner's gatekeepers must not tally its UP
    # reports; the join then completes through the fallback once the fast
    # round stalls below quorum.
    n = 100
    vc = VirtualCluster.create(n, n_slots=104, fd_threshold=2, fallback_rounds=3, seed=15)
    cohort_of = np.zeros(vc.cfg.n, dtype=np.int32)
    cohort_of[60:] = 1  # 40% of members never see the join alerts
    vc.assign_cohorts(cohort_of)
    rx = np.zeros((vc.cfg.c, vc.cfg.n), dtype=bool)
    rx[1, :] = True  # cohort 1 hears nobody (one-way ingress loss)
    vc.set_rx_block(rx)
    joiners = [100, 101, 102, 103]
    vc.inject_join_wave(joiners)
    # Cohort 1 tallied nothing for the joiners.
    assert not np.asarray(vc.state.report_bits)[1, joiners].any()
    rounds, events = vc.run_until_converged(max_steps=64)
    assert events is not None
    assert vc.membership_size == n + len(joiners)
    # Fast round could not decide (60 < quorum of 75): the decision landed
    # in the round where the fallback timer fired, not before it.
    assert rounds >= vc.cfg.fallback_rounds


def test_classic_round_coordinator_rotation_survives_blocked_coordinators():
    # Message-level classic fallback: the first pseudo-randomly picked
    # coordinators are rx-blocked from the majority cohort, so their phase-1
    # quorums fail; rotation must land on a reachable coordinator and commit.
    n = 60
    h, l = 7, 3  # margin: cut detection tolerates a few blocked observer rings
    vc = VirtualCluster.create(n, h=h, l=l, fd_threshold=2, fallback_rounds=3, seed=13)
    cohort_of = np.zeros(n, dtype=np.int32)
    cohort_of[40:] = 1
    vc.assign_cohorts(cohort_of)
    victim = 25
    vc.crash([victim])
    rx = np.zeros((vc.cfg.c, vc.cfg.n), dtype=bool)
    # Cohort 1 never hears any of victim's observers: it never proposes or
    # fast-votes, so the fast round is stuck at 40 < quorum votes.
    obs_of_victim = np.asarray(vc.state.obs_idx)[:, victim]
    rx[1, obs_of_victim] = True
    # Cohort 0 (the majority) is deaf to exactly the first two coordinators
    # the deterministic rotation will pick.
    from rapid_tpu.models.virtual_cluster import classic_coordinator_targets

    active = [i for i in range(n) if i != victim]
    blocked = []
    for epoch in range(2):
        (target,) = classic_coordinator_targets(epoch, len(active), racers=1)
        blocked.append(active[target - 1])
    rx[0, blocked] = True
    # Deterministic precondition: blocking those slots costs cohort 0 at most
    # (K - H) of the victim's rings, so its cut detection still crosses H.
    rings_lost = sum(1 for slot in obs_of_victim.tolist() if slot in set(blocked))
    assert rings_lost <= vc.cfg.k - h, "test setup would starve cut detection"
    vc.set_rx_block(rx)
    rounds, events = vc.run_until_converged(max_steps=96)
    assert events is not None
    assert not vc.alive_mask[victim]
    assert vc.membership_size == n - 1
    # Rotation was actually needed — and the run is fully deterministic:
    # alerts fire at round fd_threshold(2), the proposal goes undecided for
    # fallback_rounds(3) rounds, the first classic attempt fires in round 4
    # (undec hits 3), epochs 0 and 1 hit the two blocked coordinators
    # (rounds 4, 5), and epoch 2 commits in round 6. A reachable first pick
    # would decide at round 4.
    assert rounds == 6


def test_asymmetric_cohorts_conflicting_proposals_blocked_then_resolved():
    # Cohort 1 misses alerts from half the observers (one-way partition):
    # receivers disagree transiently, but quorum still removes the victim.
    n = 100
    vc = VirtualCluster.create(n, fd_threshold=2, seed=8)
    cohort_of = np.zeros(n, dtype=np.int32)
    cohort_of[50:] = 1
    vc.assign_cohorts(cohort_of)
    victim = 30
    vc.crash([victim])
    rx_block = np.zeros((vc.cfg.c, vc.cfg.n), dtype=bool)
    # Cohort 1 cannot hear from slots 0..9 (some of which observe the victim).
    rx_block[1, :10] = True
    vc.set_rx_block(rx_block)
    rounds, events = vc.run_until_converged(max_steps=96)
    assert events is not None
    assert vc.membership_size == n - 1
    assert not vc.alive_mask[victim]


def test_many_cohorts_with_delivery_jitter_converges():
    # 64 independently-jittered receiver cohorts (past the old uint32-packed
    # limit of 30): delivery delays make cohorts hear alert subsets at
    # different times, yet the fast round still reaches quorum on the full
    # cut once deliveries mature.
    n = 256
    vc = VirtualCluster.create(
        n, cohorts=64, fd_threshold=2, seed=3, delivery_spread=3
    )
    vc.assign_cohorts_roundrobin()
    victims = [7, 100, 201]
    vc.crash(victims)
    rounds, events = vc.run_until_converged(max_steps=96)
    assert events is not None
    assert vc.membership_size == n - len(victims)
    assert not vc.alive_mask[victims].any()


def test_delivery_jitter_causes_receiver_divergence():
    # With staggered detection AND delivery jitter, different cohorts must
    # announce different proposals in at least one run — the receiver
    # divergence regime that almost-everywhere agreement is about.
    n = 128
    c = 32
    saw_divergence = False
    for seed in range(6):
        vc = VirtualCluster.create(
            n, cohorts=c, k=10, h=6, l=2, fd_threshold=2, seed=seed,
            delivery_spread=4,
        )
        vc.assign_cohorts_roundrobin()
        rng = np.random.default_rng(seed)
        vc.stagger_fd_counts(rng, spread_rounds=3)
        victims = rng.choice(n, size=4, replace=False)
        vc.crash(victims)
        proposals = set()
        for _ in range(64):
            events = vc.step()
            announced = np.asarray(events.proposals_announced)
            if announced.any():
                # Events carry the pre-view-change hashes; state.prop_* is
                # already reset on a deciding round.
                hi = np.asarray(events.prop_hi)
                lo = np.asarray(events.prop_lo)
                for ci in np.nonzero(announced)[0]:
                    proposals.add((int(hi[ci]), int(lo[ci])))
            if bool(events.decided):
                break
        assert bool(events.decided), "run did not converge under jitter"
        if len(proposals) > 1:
            saw_divergence = True
            break
    assert saw_divergence, "no run produced divergent cohort proposals"


def test_delivery_prob_zero_means_no_divergence():
    # delivery_prob_permille=0 with a nonzero spread draws every delay as 0:
    # all cohorts hear identical alert subsets each round, so every announced
    # proposal is the same cut — the "no timing divergence" end of the
    # sub-round skew dial.
    n = 128
    for seed in range(4):
        vc = VirtualCluster.create(
            n, cohorts=32, k=10, h=6, l=2, fd_threshold=2, seed=seed,
            delivery_spread=4, delivery_prob_permille=0,
        )
        vc.assign_cohorts_roundrobin()
        rng = np.random.default_rng(seed)
        vc.stagger_fd_counts(rng, spread_rounds=3)
        vc.crash(rng.choice(n, size=4, replace=False))
        proposals = set()
        for _ in range(64):
            events = vc.step()
            announced = np.asarray(events.proposals_announced)
            if announced.any():
                hi = np.asarray(events.prop_hi)
                lo = np.asarray(events.prop_lo)
                for ci in np.nonzero(announced)[0]:
                    proposals.add((int(hi[ci]), int(lo[ci])))
            if bool(events.decided):
                break
        assert bool(events.decided)
        assert len(proposals) == 1, "prob=0 must eliminate cohort divergence"


def test_delivery_prob_sets_first_round_delivered_fraction():
    # The sub-round dial's distribution, measured directly: with spread=1 a
    # (cohort, edge) delivery is delayed one round with probability
    # permille/1000, so the fraction of (cohort, edge) alert bits landing in
    # the fire round itself must track 1 - p. (permille=1000 keeps the
    # original uniform [0, spread] draw: p = 1/2.)
    n = 64
    c = 256

    def first_round_fraction(permille: int) -> float:
        vc = VirtualCluster.create(
            n, cohorts=c, k=10, h=9, l=4, fd_threshold=1, seed=5,
            delivery_spread=1, delivery_prob_permille=permille,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash([11])
        events = vc.step()  # detectors fire and delay-0 deliveries land
        assert not bool(events.decided)
        bits = np.asarray(vc.state.report_bits)  # [c, n] uint32
        delivered = sum(bin(int(b)).count("1") for b in bits[:, 11])
        fired = int(np.asarray(vc.state.fd_fired)[11].sum())
        assert fired > 0
        return delivered / (c * fired)

    frac_low = first_round_fraction(250)
    frac_full = first_round_fraction(1000)
    assert 0.65 < frac_low < 0.85, frac_low  # expect ~0.75
    assert 0.40 < frac_full < 0.60, frac_full  # expect ~0.5

    # Out-of-range probabilities fail fast (negative would wrap through
    # uint32 in the delivery gate and silently mean p=1).
    import pytest

    with pytest.raises(ValueError):
        VirtualCluster.create(16, delivery_spread=1, delivery_prob_permille=-1)
    with pytest.raises(ValueError):
        VirtualCluster.create(16, delivery_spread=1, delivery_prob_permille=1001)


def test_rx_block_past_word_boundary():
    # Cohort indices above 31 live in the second packed uint32 word; a
    # blocked cohort there must genuinely miss alerts (regression for the
    # bit-packing over cohorts).
    n = 96
    c = 40
    vc = VirtualCluster.create(n, cohorts=c, fd_threshold=2, seed=4)
    vc.assign_cohorts_roundrobin()
    victim = 11
    vc.crash([victim])
    # Cohort 35 (word 1, bit 3) is blocked from EVERY observer: it can never
    # hear any alert, so its report bits must stay empty.
    rx_block = np.zeros((c, n), dtype=bool)
    rx_block[35, :] = True
    vc.set_rx_block(rx_block)
    # Track which cohorts ever announce a proposal: the fully-blocked cohort
    # must never hear anything, hence never propose; others must.
    announced_union = np.zeros(c, dtype=bool)
    decided = False
    for _ in range(64):
        events = vc.step()
        announced_union |= np.asarray(events.proposals_announced)
        if bool(events.decided):
            decided = True
            break
    assert decided  # quorum of unblocked cohorts still decides
    assert not vc.alive_mask[victim]
    assert not announced_union[35], "blocked cohort (word 1, bit 3) heard alerts"
    assert announced_union.sum() >= 1


@pytest.mark.slow
def test_concurrent_coordinators_lower_rank_phase2a_loses():
    # Rides the unfiltered check.sh pass (~11 s wall). Tier-1
    # representative of racing-coordinator rank ordering:
    # test_concurrent_coordinators_partitioned_higher_rank_lower_wins
    # (same phase1/phase2 rank machinery, plus the partition masks).
    # Two coordinators race in one classic attempt with full connectivity:
    # both win phase 1 (every acceptor promises each heard rank in order),
    # but every acceptor's final rnd is the higher rank, so the lower-ranked
    # coordinator's phase2a is rejected everywhere and only the higher rank
    # gets accepts (Paxos.java:93-97, 333-339 rank ordering).
    from rapid_tpu.models.virtual_cluster import (
        _compute_round,
        classic_coordinator_targets,
        engine_step_nodonate,
    )

    n = 120
    vc = VirtualCluster.create(
        n, fd_threshold=2, seed=11, fallback_rounds=3, concurrent_coordinators=2
    )
    cohort_of = np.zeros(n, dtype=np.int32)
    cohort_of[80:] = 1
    vc.assign_cohorts(cohort_of)
    v1, v2 = 10, 60
    vc.crash([v1, v2])
    # Cohort 1 never hears v2's observers: conflicting proposals stall the
    # fast round, forcing the classic fallback.
    rx = np.zeros((vc.cfg.c, vc.cfg.n), dtype=bool)
    obs_of_v2 = np.asarray(vc.state.obs_idx)[:, v2]
    rx[1, obs_of_v2] = True
    vc.set_rx_block(rx)

    # Drive the non-donating step so the pre-decision state stays readable.
    state, faults = vc.state, vc.faults
    for _ in range(64):
        state_before = state
        state, events = engine_step_nodonate(vc.cfg, state, faults)
        if bool(events.decided):
            break
    assert bool(events.decided)
    # Fallback decided (fast round was stuck below quorum).
    assert int(events.total_votes) > int(events.max_votes)

    # Re-run the deciding round from the captured pre-state and inspect the
    # acceptor ranks BEFORE the view change resets them.
    round_state, decided, winner_mask, _ = _compute_round(
        vc.cfg, state_before, faults
    )
    assert bool(decided)
    epoch = int(state_before.classic_epoch)
    active = np.nonzero(
        np.asarray(state_before.alive) & ~np.asarray(faults.crashed)
    )[0]
    targets = classic_coordinator_targets(epoch, len(active), 2)
    coords = [int(active[t - 1]) for t in targets]
    round_num = 2 + epoch
    acc = np.asarray(round_state.cp_vrnd_r) == round_num
    assert acc.sum() >= n // 2 + 1  # a majority accepted this attempt
    accepted_ranks = set(np.asarray(round_state.cp_vrnd_i)[acc].tolist())
    if coords[0] != coords[1]:
        hi, lo = max(coords), min(coords)
        # Rank order: the higher-indexed racer's rank wins everywhere (full
        # connectivity for phase 1a), so no acceptor holds the lower rank.
        assert accepted_ranks == {hi}
        assert lo not in accepted_ranks
    else:
        assert accepted_ranks == {coords[0]}
    # The decided cut is still exactly the plurality proposal.
    winner = set(np.nonzero(np.asarray(events.winner_mask))[0].tolist())
    assert winner == {v1, v2}


def test_concurrent_coordinators_partitioned_higher_rank_lower_wins():
    # The higher-ranked racer is rx-blocked from everybody: its phase 1
    # fails, while the lower-ranked racer (reachable by all) completes both
    # phases among acceptors that never heard the higher rank's phase1a.
    from rapid_tpu.models.virtual_cluster import (
        _compute_round,
        classic_coordinator_targets,
        engine_step_nodonate,
    )

    n = 60
    h, l = 7, 3
    vc = VirtualCluster.create(
        n, h=h, l=l, fd_threshold=2, seed=13, fallback_rounds=3,
        concurrent_coordinators=2,
    )
    cohort_of = np.zeros(n, dtype=np.int32)
    cohort_of[40:] = 1
    vc.assign_cohorts(cohort_of)
    victim = 25
    vc.crash([victim])
    rx = np.zeros((vc.cfg.c, vc.cfg.n), dtype=bool)
    obs_of_victim = np.asarray(vc.state.obs_idx)[:, victim]
    rx[1, obs_of_victim] = True  # cohort 1 stuck -> fast round below quorum
    # Predict epoch-0 racers; block the HIGHER-ranked one from both cohorts.
    active = [i for i in range(n) if i != victim]
    targets = classic_coordinator_targets(0, len(active), 2)
    coords0 = [active[t - 1] for t in targets]
    hi, lo = max(coords0), min(coords0)
    # Pinned preconditions (not skips): with seed=13, n=60, victim=25 the
    # epoch-0 rotation picks racers {13, 35} and the higher one observes the
    # victim on <= k-h rings, so the scenario this test exists for actually
    # runs. If a _rotation_seed/ring-hash refactor breaks either, fail loudly
    # and re-pin a seed (any seed in 0..29 satisfied both at pin time).
    assert hi != lo, f"rotation no longer yields distinct racers ({coords0})"
    rings_lost = sum(1 for s in obs_of_victim.tolist() if s == hi)
    assert rings_lost <= vc.cfg.k - h, (
        f"blocking racer {hi} would starve cut detection "
        f"({rings_lost} of victim's rings > k-h={vc.cfg.k - h}); re-pin seed"
    )
    rx[:, hi] = True  # nobody hears the higher-ranked coordinator
    vc.set_rx_block(rx)

    state, faults = vc.state, vc.faults
    for _ in range(64):
        state_before = state
        state, events = engine_step_nodonate(vc.cfg, state, faults)
        if bool(events.decided):
            break
    assert bool(events.decided)
    assert not np.asarray(state.alive)[victim]

    round_state, decided, _, _ = _compute_round(vc.cfg, state_before, faults)
    assert bool(decided)
    epoch = int(state_before.classic_epoch)
    if epoch == 0:
        # Decided on the contested attempt: acceptors hold the LOWER rank.
        acc = np.asarray(round_state.cp_vrnd_r) == 2
        accepted_ranks = set(np.asarray(round_state.cp_vrnd_i)[acc].tolist())
        assert lo in accepted_ranks
        assert hi not in accepted_ranks


def test_join_reports_respect_delivery_jitter():
    # Join (UP) gatekeeper reports ride the same delayed-delivery machinery
    # as DOWN alerts: with a delivery spread, some cohorts hear a joiner's
    # rings strictly later, so the join cut takes at least as many rounds as
    # the zero-jitter run — and never decides before ANY ring could arrive.
    def run(spread):
        vc = VirtualCluster.create(
            60, n_slots=72, cohorts=16, fd_threshold=2, seed=21,
            delivery_spread=spread,
        )
        vc.assign_cohorts_roundrobin()
        vc.inject_join_wave(list(range(60, 72)))
        rounds, events = vc.run_until_converged(max_steps=64)
        assert events is not None
        assert vc.membership_size == 72
        return rounds

    fast = run(0)
    slow = run(5)
    # Strict: with 16 cohorts x 12 joiners x 10 rings and spread 5, the
    # deterministic per-(cohort, edge) hash draws make at least one needed
    # ring arrive late in every cohort's tally — equality would mean the
    # jitter was ignored entirely.
    assert slow > fast


def test_healed_partition_redelivers_old_alerts():
    # A cohort blocked from every observer misses the DOWN alerts; after the
    # delivery window matures the round body cond-skips delivery work. When
    # the partition heals mid-configuration (set_rx_block), the old alerts
    # must still reach the healed cohort (fired edges are re-stamped), or
    # the fast round would stay short of quorum forever.
    n = 100
    vc = VirtualCluster.create(
        n, cohorts=2, fd_threshold=2, seed=31, delivery_spread=2,
        fallback_rounds=64,  # keep the classic fallback out of the way
    )
    cohort_of = np.zeros(n, dtype=np.int32)
    cohort_of[60:] = 1  # 40% of members: fast quorum unreachable without them
    vc.assign_cohorts(cohort_of)
    victim = 33
    vc.crash([victim])
    rx = np.zeros((2, n), dtype=bool)
    rx[1, :] = True  # cohort 1 hears nobody
    vc.set_rx_block(rx)
    for _ in range(20):  # well past max(fire_round) + spread
        events = vc.step()
        assert not bool(events.decided)
    assert int(np.asarray(vc.state.report_bits)[1].sum()) == 0
    # Heal the partition: cohort 1 must now receive the OLD alerts.
    vc.set_rx_block(np.zeros((2, n), dtype=bool))
    decided = False
    for _ in range(16):
        events = vc.step()
        if bool(events.decided):
            decided = True
            break
    assert decided, "healed cohort never received re-delivered alerts"
    assert not vc.alive_mask[victim]


def test_pending_joiner_survives_intervening_view_change():
    # A joiner whose gatekeeper alerts are blocked for every cohort misses
    # the first cut (a DOWN-only view change from a concurrent crash). Its
    # UP edges must stay armed ACROSS that view change: once the block
    # heals, the alerts redeliver in the new configuration and a later cut
    # admits it — previously the view change wiped the fired-edge state and
    # the joiner was stranded forever.
    n = 100
    h, l = 7, 3  # margin: blocking gatekeepers may cost other subjects rings
    vc = VirtualCluster.create(n, n_slots=101, h=h, l=l, cohorts=2,
                               fd_threshold=2, seed=41)
    cohort_of = np.zeros(vc.cfg.n, dtype=np.int32)
    cohort_of[50:] = 1
    vc.assign_cohorts(cohort_of)
    joiner = 100
    vc.inject_join_wave([joiner])
    gatekeepers = np.unique(np.asarray(vc.state.obs_idx)[:, joiner])
    gatekeepers = set(gatekeepers[gatekeepers >= 0].tolist())
    # Pick a victim whose cut detection survives the gatekeeper block: at
    # most K - H of its observer rings may be blocked.
    obs = np.asarray(vc.state.obs_idx)
    victim = None
    for cand in range(n):
        overlap = sum(1 for s in obs[:, cand].tolist() if s in gatekeepers)
        if cand not in gatekeepers and overlap <= vc.cfg.k - h:
            victim = cand
            break
    assert victim is not None, "no victim candidate clears the precondition"
    rx = np.zeros((vc.cfg.c, vc.cfg.n), dtype=bool)
    rx[:, sorted(gatekeepers)] = True
    vc.set_rx_block(rx)
    vc.crash([victim])

    rounds, events = vc.run_until_converged(max_steps=48)
    assert events is not None
    # First cut: DOWN-only (the joiner's reports never arrived anywhere).
    assert not vc.alive_mask[victim]
    assert vc.membership_size == n - 1
    assert bool(np.asarray(vc.state.join_pending)[joiner])

    # Heal: the joiner's old UP alerts must redeliver in the NEW config.
    vc.set_rx_block(np.zeros((vc.cfg.c, vc.cfg.n), dtype=bool))
    rounds, events = vc.run_until_converged(max_steps=48)
    assert events is not None, "stranded joiner: UP edges were wiped by the view change"
    assert vc.membership_size == n
    assert bool(vc.alive_mask[joiner])


def test_graceful_leave_converges_faster_than_crash():
    # A graceful leave pre-fires the DOWN alerts (LeaveMessage semantics):
    # the cut must commit without waiting fd_threshold probe windows, i.e.
    # strictly faster than detecting the same member crashing.
    def run(leave: bool):
        vc = VirtualCluster.create(80, fd_threshold=4, seed=51)
        if leave:
            vc.initiate_leave([12, 40])
        else:
            vc.crash([12, 40])
        rounds, events = vc.run_until_converged(max_steps=32)
        assert events is not None
        assert vc.membership_size == 78
        assert not vc.alive_mask[[12, 40]].any()
        return rounds

    leave_rounds = run(True)
    crash_rounds = run(False)
    assert leave_rounds < crash_rounds
    # No detection delay at all: decision lands within a couple of rounds.
    assert leave_rounds <= 3


def test_rejoin_after_removal_uses_fresh_slot():
    # Engine rejoin discipline: a removed member comes back through a FRESH
    # slot (new identity lanes), mirroring the reference's new-UUID rejoin
    # rule. The configuration id after rejoin must differ from every earlier
    # configuration even though the "same node" is back.
    vc = VirtualCluster.create(50, n_slots=52, fd_threshold=2, seed=61)
    config0 = vc.config_id
    victim = 9
    vc.crash([victim])
    rounds, events = vc.run_until_converged(max_steps=32)
    assert events is not None
    config1 = vc.config_id
    assert config1 != config0
    # The node returns as a new identity in slot 50.
    vc.inject_join_wave([50])
    rounds, events = vc.run_until_converged(max_steps=32)
    assert events is not None
    assert vc.membership_size == 50
    assert bool(vc.alive_mask[50]) and not vc.alive_mask[victim]
    config2 = vc.config_id
    assert config2 not in (config0, config1)


def test_readmitting_retired_slot_is_rejected():
    # The engine's UUIDAlreadySeenError: identity lanes of a removed member
    # are spent — re-admitting the slot would replay a prior configuration
    # id, so inject_join_wave must refuse it.
    import pytest

    vc = VirtualCluster.create(50, n_slots=52, fd_threshold=2, seed=62)
    vc.crash([9])
    rounds, events = vc.run_until_converged(max_steps=32)
    assert events is not None and not vc.alive_mask[9]
    with pytest.raises(ValueError, match="retired"):
        vc.inject_join_wave([9])
    # Current members and already-pending joiners are equally inadmissible.
    with pytest.raises(ValueError):
        vc.inject_join_wave([3])
    vc.inject_join_wave([50])
    with pytest.raises(ValueError):
        vc.inject_join_wave([50])


@pytest.mark.slow
def test_windowed_fd_mode_forgives_intermittent_blips():
    # Rides the unfiltered check.sh pass (~15 s wall). Tier-1
    # representatives of the windowed policy: the host<->device agreement
    # oracle test_windowed_fd.py::test_host_and_device_windowed_rules_agree
    # and the host-side policy table in the same file.
    # Device-side windowed policy (cfg.fd_window, the paper's rule): an edge
    # failing 1 round in every 4 never accumulates fd_threshold failures
    # within the window, so it NEVER fires — while the reference code's
    # cumulative counter latches every blip and eventually evicts. A
    # persistent failure still fires in both modes.
    n = 60

    def run(fd_window, flaky_period, rounds):
        vc = VirtualCluster.create(
            n, k=10, h=7, l=3, fd_threshold=4, seed=71, fd_window=fd_window
        )
        probe_fail = np.zeros((vc.cfg.n, vc.cfg.k), dtype=bool)
        on = np.zeros_like(probe_fail)
        on[13, :] = True  # all of subject 13's edges blip together
        decided = False
        for r in range(rounds):
            vc.set_flaky_edges(on if r % flaky_period == 0 else probe_fail)
            events = vc.step()
            decided |= bool(events.decided)
        return decided, vc

    # Intermittent (1-in-4): windowed mode (window 8, threshold 4) forgives.
    decided, vc = run(fd_window=8, flaky_period=4, rounds=40)
    assert not decided
    assert vc.membership_size == n
    # Same blips under the cumulative counter: latched and evicted.
    decided, vc = run(fd_window=0, flaky_period=4, rounds=40)
    assert decided
    assert vc.membership_size == n - 1

    # Persistent failure fires in windowed mode too — but never before a
    # FULL window of probes has been observed (host-twin parity: the
    # sliding window must fill first).
    vc = VirtualCluster.create(n, fd_threshold=4, seed=72, fd_window=8)
    vc.crash([21])
    rounds, events = vc.run_until_converged(max_steps=32)
    assert events is not None
    assert not vc.alive_mask[21]
    assert rounds >= 8


@pytest.mark.slow
def test_ring_count_boundaries_converge():
    # K=3 (the protocol minimum) and K=32 (the uint32 ring-bitmask width)
    # must both drive a full crash convergence — no hidden K=10 assumptions
    # in packing, delivery, or the watermark pass.
    # Rides the unfiltered check.sh pass (~17 s wall: three full engine
    # compiles at distinct K); the K=10 suite above keeps every protocol
    # outcome in tier-1.
    for k, h, l in ((3, 3, 1), (16, 14, 5), (32, 29, 10)):
        vc = VirtualCluster.create(
            80, k=k, h=h, l=l, fd_threshold=2, seed=81, cohorts=4,
            delivery_spread=1,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash([11, 42])
        rounds, events = vc.run_until_converged(max_steps=48)
        assert events is not None, f"K={k} did not converge"
        assert vc.membership_size == 78
        assert not vc.alive_mask[[11, 42]].any()


def test_run_until_membership_matches_sequential_decisions():
    # The multi-cut single-dispatch loop must commit exactly the cuts the
    # sequential per-decision driver commits: same rounds, same cut count,
    # same final membership/config — it only removes host round trips.
    def build():
        vc = VirtualCluster.create(
            60, n_slots=72, cohorts=16, fd_threshold=2, seed=11,
            delivery_spread=1,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash([7, 31])
        vc.inject_join_wave(list(range(60, 72)))
        return vc

    # Sequential reference: one dispatch per cut.
    seq = build()
    seq_rounds, seq_cuts = 0, 0
    while seq.membership_size != 70:
        rounds, decided, _, _ = seq.run_to_decision(max_steps=64)
        assert decided
        seq_rounds += rounds
        seq_cuts += 1
        assert seq_cuts <= 8

    fused = build()
    rounds, cuts, resolved, sizes = fused.run_until_membership(70)
    assert resolved
    assert (rounds, cuts) == (seq_rounds, seq_cuts)
    assert fused.membership_size == 70
    assert len(sizes) == cuts and sizes[-1] == 70  # Table 1 instrument
    np.testing.assert_array_equal(fused.alive_mask, seq.alive_mask)
    assert fused.config_id == seq.config_id


def test_run_until_membership_reports_unresolved_on_budget():
    # An unreachable target must come back resolved=False with the stall
    # latched (no spin): nothing here ever crashes, so no cut can form.
    vc = VirtualCluster.create(20, fd_threshold=2, seed=0)
    rounds, cuts, resolved, sizes = vc.run_until_membership(5, max_steps=16)
    assert not resolved
    assert cuts == 0 and sizes == ()
    assert vc.membership_size == 20


def test_run_until_membership_equal_churn_needs_min_cuts():
    # J joins + J crashes target the STARTING membership: without min_cuts
    # the loop would resolve vacuously before any cut; with min_cuts=1 it
    # must actually run the churn to completion.
    def build():
        vc = VirtualCluster.create(40, n_slots=44, fd_threshold=2, seed=3)
        vc.crash([5, 11, 21, 33])
        vc.inject_join_wave([40, 41, 42, 43])
        return vc

    vacuous = build()
    rounds, cuts, resolved, _ = vacuous.run_until_membership(40)
    assert resolved and cuts == 0 and rounds == 0  # the documented trap

    vc = build()
    rounds, cuts, resolved, sizes = vc.run_until_membership(40, min_cuts=1)
    assert resolved and cuts >= 1 and rounds > 0
    assert vc.membership_size == 40
    assert not vc.alive_mask[[5, 11, 21, 33]].any()
    assert vc.alive_mask[40:44].all()
