"""The compiled-program conformance gate (analysis family 12-13 pins).

What must hold, per ISSUE 8's acceptance criteria:

- ``staticcheck --update-hlo-lock`` on a clean tree is a byte-identical
  round trip against the committed ``tools/analysis/hlo.lock.json``;
- an injected hot-loop all-gather in a corpus-compiled program fails the
  gate naming the entrypoint, the location class, and the payload delta;
- every registered engine entrypoint's ``donate_argnums`` buffers are
  verified aliased in the compiled output (or carry an explicit waiver),
  on the forced 8-device CPU mesh — no TPU required;
- the payload accounting never guesses an unknown dtype;
- each registered entrypoint recalled with fresh same-shape inputs does
  NOT recompile (the executable check behind ``retrace-hazard``).

The entrypoint compiles are collected once per process
(``collect_facts``'s session cache) and shared with the tree sweeps in
test_lint.py / test_staticcheck.py.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import staticcheck  # noqa: E402
from analysis import device_program, hlo_facts  # noqa: E402

CORPUS = REPO / "tests" / "data" / "lint_corpus"


# ---------------------------------------------------------------------------
# Payload accounting (the _shape_bytes dtype-table satellite)
# ---------------------------------------------------------------------------


def test_shape_bytes_covers_narrow_and_complex_dtypes():
    # The dtypes the old table silently guessed as 4 bytes each.
    assert hlo_facts.shape_bytes("f8e4m3[8]") == 8
    assert hlo_facts.shape_bytes("f8e5m2[16]{0}") == 16
    assert hlo_facts.shape_bytes("s4[16]") == 8  # two elements per byte
    assert hlo_facts.shape_bytes("u4[7]") == 4  # rounds UP to whole bytes
    assert hlo_facts.shape_bytes("c64[2]") == 16
    assert hlo_facts.shape_bytes("c128[2]") == 32


def test_shape_bytes_tuple_shapes_with_nested_layouts():
    # Layout annotations ({1,0}) are not shape tokens; scalars ([]) are one
    # element.
    assert hlo_facts.shape_bytes("(u32[64]{0}, bf16[2,3]{1,0})") == 256 + 12
    assert hlo_facts.shape_bytes("(f32[], (pred[8]{0}, s64[2,2]{1,0}))") == (
        4 + 8 + 32
    )


def test_unknown_dtype_is_never_a_silent_guess():
    with pytest.raises(ValueError, match="unknown HLO dtype 'q7'"):
        hlo_facts.shape_bytes("q7[4]")
    unknown = []
    assert hlo_facts.shape_bytes("(q7[4], u32[2])", unknown=unknown) == 8
    assert unknown == ["q7"]


def test_unknown_dtype_surfaces_as_a_finding():
    entry = {
        "collectives": {}, "transfers": {}, "memory": {},
        "donation": {"donated_leaves": 0, "aliased": 0, "dropped": 0},
        "unknown_dtypes": ["q7"],
    }
    findings = device_program.compare_facts("probe", entry, {}, ("hlo.lock", 1))
    assert [f.check for f in findings] == ["hlo-unknown-dtype"]
    assert "q7" in findings[0].message and "do not guess" in findings[0].message


# ---------------------------------------------------------------------------
# The committed lock: clean gate + byte-identical regeneration
# ---------------------------------------------------------------------------


def test_registered_entrypoints_audit_clean_against_committed_lock():
    # The real gate over the real engine on the forced 8-device CPU mesh —
    # and the session cache is real (every later sweep reuses this compile
    # round). When THIS call is the session's first collection (it is, in
    # both tier-1 and check.sh ordering), it pays the fresh backend
    # compiles — budget them here, where the cost is guaranteed to be
    # real (test_lint's sweep budget would otherwise measure a cache hit).
    # 90 s since the tenant-fleet pair joined the registry (nine
    # entrypoints; two- and three-axis GSPMD partitioning costs real
    # compile time — the compile-inclusive budget may grow, the
    # analysis-only sweep budget in test_lint.py must not).
    import time

    fresh = device_program._FACTS_CACHE is None
    started = time.process_time()
    facts = staticcheck.collect_facts()
    elapsed = time.process_time() - started
    if fresh:
        assert elapsed < 90.0, (
            f"fresh entrypoint compile collection used {elapsed:.1f}s CPU "
            f"(budget 90s)"
        )
    assert set(facts) == {
        "step", "run_to_decision", "run_until_membership", "sync",
        "step_compact", "step_telem", "step_trace",
        "sharded_step", "sharded_step_telem", "sharded_wave",
        "sharded2d_wave",
        "fleet3d_step", "fleet3d_wave",
    }
    trees = [(None, rel) for rel in device_program.REGISTRY_SOURCES]
    assert device_program.check_hlo_lock(trees) == []
    assert staticcheck.collect_facts() is facts  # cached, not recompiled


def test_sharded_entrypoints_have_collectives_single_device_do_not():
    facts = staticcheck.collect_facts()
    for name in ("sharded_step", "sharded_step_telem", "sharded_wave",
                 "sharded2d_wave", "fleet3d_step", "fleet3d_wave"):
        assert facts[name]["collectives"], name
    for name in ("step", "run_to_decision", "run_until_membership", "sync",
                 "step_compact", "step_telem", "step_trace"):
        assert facts[name]["collectives"] == {}, name
    # Both waves' unconditional hot loops stay reduce-class at scalar/[n]
    # payloads; [c,n]-scale traffic is cond-gated — the parallel/audit
    # invariant, now lockfile-frozen for the 1-D AND the 2-D mesh.
    for name in ("sharded_wave", "sharded2d_wave"):
        for key, entry in facts[name]["collectives"].items():
            if key.startswith("hot-loop/"):
                assert entry["class"] in ("scalar", "n"), (name, key, entry)
                assert key == "hot-loop/all-reduce", (name, key, entry)


def test_2d_wave_hot_loop_adds_no_new_collectives_vs_1d_baseline():
    """ISSUE 9 acceptance: on the forced 8-device mesh the 2-D
    ('cohort','nodes') wave compiles with every donated leaf aliased and
    NO hot-loop collective kind the 1-D baseline lock does not already
    carry — meshing the cohort axis must not smuggle new unconditional
    traffic into the convergence hot loop."""
    facts = staticcheck.collect_facts()
    baseline = json.loads((REPO / staticcheck.HLO_LOCK_REL).read_text())
    locked_1d = baseline["entrypoints"]["sharded_wave"]["collectives"]

    def hot_kinds(colls):
        return {k for k in colls if k.startswith("hot-loop/")}

    donation = facts["sharded2d_wave"]["donation"]
    assert donation["dropped"] == 0
    assert donation["aliased"] == donation["donated_leaves"] > 0
    assert hot_kinds(facts["sharded2d_wave"]["collectives"]) <= hot_kinds(
        locked_1d
    ), (
        facts["sharded2d_wave"]["collectives"],
        locked_1d,
    )


def test_2d_cohort_state_memory_is_sharded_not_replicated():
    """ISSUE 9 acceptance, asserted from memory_analysis(): with the rule
    table's cohort-axis specs, per-device [c]/[c,n] state bytes are
    1/cohort-axis-size of what the SAME 2-D mesh pays when the cohort axis
    is left unmeshed (the old `replicated-ok` layout) — the compiled
    program's own argument accounting shows the saving."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from rapid_tpu.models.virtual_cluster import (
        VirtualCluster,
        engine_step_impl,
    )
    from rapid_tpu.parallel.mesh import (
        COHORT_AXIS,
        fault_shardings,
        make_mesh,
        state_shardings,
    )

    n, c = device_program.AUDIT_N, device_program.AUDIT_C
    dc = device_program.AUDIT_COHORT_DEVICES
    dn = device_program.AUDIT_DEVICES // dc
    vc = VirtualCluster.create(
        n - 8, n_slots=n, k=device_program.AUDIT_K, h=3, l=1, fd_threshold=2,
        cohorts=c, delivery_spread=2, seed=0,
    )
    vc.assign_cohorts_roundrobin()
    cfg = vc.cfg
    mesh = make_mesh(jax.devices()[:8], shape=(dc, dn))
    rules_st = state_shardings(mesh)
    rules_ft = fault_shardings(mesh)

    def drop_cohort(sh):
        return NamedSharding(
            sh.mesh, P(*(None if ax == COHORT_AXIS else ax for ax in sh.spec))
        )

    repl_st = jax.tree.map(drop_cohort, rules_st)
    repl_ft = jax.tree.map(drop_cohort, rules_ft)

    # The rules-table side IS the registry's sharded2d_wave (identical cfg
    # + shardings): reuse its session-cached memory facts; only the
    # cohort-replicated counterfactual needs a fresh compile — the STEP
    # program, whose (state, faults) arguments are byte-identical to the
    # wave's modulo three trailing int32 scalars (12 bytes of noise
    # against a ~KB saving), at roughly half the wave's compile cost.
    del rules_st, rules_ft
    rules_args = staticcheck.collect_facts()["sharded2d_wave"]["memory"][
        "argument_bytes"
    ]
    repl_args = (
        jax.jit(
            lambda s, f: engine_step_impl(cfg, s, f),
            in_shardings=(repl_st, repl_ft),
            donate_argnums=(0,),
        )
        .lower(vc.state, vc.faults)
        .compile()
        .memory_analysis()
        .argument_size_in_bytes
    )
    cohort_leaves = (
        vc.state.report_bits, vc.state.released, vc.state.prop_mask,
        vc.faults.rx_block, vc.state.seen_down, vc.state.announced,
        vc.state.prop_hi, vc.state.prop_lo,
    )
    global_bytes = sum(int(leaf.nbytes) for leaf in cohort_leaves)
    # Cohort-meshed leaves hold 1/(dc*dn) of global per device; the
    # unmeshed layout holds 1/dn. The argument accounting must show at
    # least 90% of that saving (ε = scheduler slack on the remainder).
    expected_saving = global_bytes * (1 / dn - 1 / (dc * dn))
    saved = repl_args - rules_args
    assert saved >= 0.9 * expected_saving, (
        saved, expected_saving, repl_args, rules_args,
    )


def test_compact_entrypoints_shrink_argument_bytes():
    """ISSUE 13 acceptance, from the compiled artifact: the compact-policy
    step carries >= 30% fewer per-device argument bytes than the wide
    oracle at the audit shape (the wave's argument surface is
    byte-identical modulo three int32 control scalars — registering the
    step freezes the claim for both, the PR-9 single-representative
    convention), its entry signature actually carries the narrow dtypes
    (s16/s8/u8 — the policy landed, not just the formula), donation stays
    fully aliased, and its hot-loop collective and transfer budgets match
    the wide twin's (empty/none on the single-device audit programs —
    compaction adds no communication)."""
    facts = staticcheck.collect_facts()
    locked = json.loads((REPO / staticcheck.HLO_LOCK_REL).read_text())
    for wide_name, compact_name in (
        ("step", "step_compact"),
    ):
        wide_args = facts[wide_name]["memory"]["argument_bytes"]
        compact_args = facts[compact_name]["memory"]["argument_bytes"]
        assert compact_args <= 0.7 * wide_args, (
            wide_name, wide_args, compact_args,
        )
        assert locked["entrypoints"][compact_name]["memory"][
            "argument_bytes"
        ] == compact_args
        dtypes = facts[compact_name]["parameter_dtype_bytes"]
        assert {"s16", "s8", "u8"} <= set(dtypes), dtypes
        wide_dtypes = facts[wide_name]["parameter_dtype_bytes"]
        assert set(wide_dtypes) <= {"pred", "s32", "u32"}, wide_dtypes
        donation = facts[compact_name]["donation"]
        assert donation["dropped"] == 0
        assert donation["aliased"] == donation["donated_leaves"] > 0
        # No new hot-loop collectives and no host<->device transfers vs
        # the wide twin.
        hot_wide = {
            k for k in facts[wide_name]["collectives"] if k.startswith("hot-loop/")
        }
        hot_compact = {
            k for k in facts[compact_name]["collectives"]
            if k.startswith("hot-loop/")
        }
        assert hot_compact <= hot_wide
        assert facts[compact_name]["transfers"] == facts[wide_name]["transfers"]


def test_compact_formula_matches_compiled_argument_bytes():
    """The bench's bytes/member formula (models/state.state_bytes_total) is
    the compiled artifact's own argument accounting: state+faults bytes at
    the audit geometry equal memory_analysis()'s argument bytes minus the
    non-state scalars (the wave carries three int32 control scalars; the
    step none)."""
    from rapid_tpu.models.state import EngineConfig, state_bytes_total

    facts = staticcheck.collect_facts()
    cfg = EngineConfig(
        n=device_program.AUDIT_N, k=device_program.AUDIT_K, h=3, l=1,
        c=device_program.AUDIT_C, fd_threshold=2, delivery_spread=2,
    )
    for name, compact in (("step", 0), ("step_compact", 1)):
        formula = state_bytes_total(cfg._replace(compact=compact))
        measured = facts[name]["memory"]["argument_bytes"]
        assert measured == formula, (name, measured, formula)


def test_update_lock_refuses_on_compaction_differential_mismatch(monkeypatch):
    """`--update-hlo-lock` must not freeze memory budgets while the
    compact engine disagrees with its wide oracle: a reported mismatch
    becomes a blocking finding and no lock is written."""
    monkeypatch.setattr(
        device_program, "compaction_differential_ok",
        lambda: "wide<->compact differential disagrees on state lane 'fd_count'",
    )
    findings, path = device_program.update_hlo_lock()
    assert path is None
    assert any(
        "wide<->compact differential" in f.message for f in findings
    ), findings


def test_fleet_entrypoints_have_zero_cross_tenant_collectives():
    """ISSUE 10 acceptance: the batched step/wave compile with the tenant
    axis FULLY parallel — no collective's replica groups span tenant device
    blocks (cross_tenant_collectives == 0, frozen in the lock), and every
    donated fleet buffer is aliased. The fleet wave's hot loop may carry
    within-tenant gathers (vmap select-applies the view change — the
    batched-serving tradeoff fleet.py documents) but never cross-tenant
    traffic of ANY class."""
    facts = staticcheck.collect_facts()
    locked = json.loads((REPO / staticcheck.HLO_LOCK_REL).read_text())
    for name in ("fleet3d_step", "fleet3d_wave"):
        assert facts[name]["cross_tenant_collectives"] == 0, name
        assert locked["entrypoints"][name]["cross_tenant_collectives"] == 0
        donation = facts[name]["donation"]
        assert donation["dropped"] == 0
        assert donation["aliased"] == donation["donated_leaves"] > 0
    # The step is straight-line (no loop): all its collectives are
    # prologue-class; the wave's ride the vmapped hot loop and must be
    # classified there (a vmap(while) scope must never pass as prologue).
    assert all(
        key.startswith("prologue/")
        for key in facts["fleet3d_step"]["collectives"]
    )
    assert facts["fleet3d_wave"]["collectives"]
    assert all(
        key.startswith("hot-loop")
        for key in facts["fleet3d_wave"]["collectives"]
    )


def test_cross_tenant_collective_is_a_blocking_finding():
    """A fleet program with a tenant-spanning collective must fail the gate
    with its own check name — and can never be frozen (update refuses it,
    the dropped-donation discipline)."""
    entry = {
        "collectives": {}, "transfers": {}, "memory": {},
        "donation": {"donated_leaves": 0, "aliased": 0, "dropped": 0},
        "unknown_dtypes": [], "cross_tenant_collectives": 2,
    }
    findings = device_program.compare_facts(
        "fleet3d_step", entry, {"cross_tenant_collectives": 0}, ("hlo.lock", 1)
    )
    assert [f.check for f in findings] == ["hlo-cross-tenant-collective"]
    assert "2 collective(s)" in findings[0].message
    assert "never communicate" in findings[0].message
    # Zero-vs-locked drift (a lock claiming nonzero) is ordinary drift.
    entry["cross_tenant_collectives"] = 0
    findings = device_program.compare_facts(
        "fleet3d_step", entry, {"cross_tenant_collectives": 1}, ("hlo.lock", 1)
    )
    assert [f.check for f in findings] == ["hlo-lock-drift"]


def test_replica_group_parsing_covers_all_hlo_spellings():
    """The cross-tenant check's parser: explicit-list replica_groups, the
    iota v2 form (with and without transpose), collective-permute
    source_target_pairs, and the all-participants default."""
    groups = hlo_facts.collective_groups(
        'x = u32[8] all-reduce(y), replica_groups={{0,1},{2,3},{4,5},{6,7}}'
    )
    assert groups == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert hlo_facts.collective_groups(
        'x = u32[8] all-gather(y), replica_groups=[4,2]<=[8], dimensions={0}'
    ) == [[0, 1], [2, 3], [4, 5], [6, 7]]
    # Transposed iota: arange(8).reshape(2,2,2).transpose(0,2,1) rows.
    assert hlo_facts.collective_groups(
        'x = u32[8] all-gather(y), replica_groups=[4,2]<=[2,2,2]T(0,2,1)'
    ) == [[0, 2], [1, 3], [4, 6], [5, 7]]
    assert hlo_facts.collective_groups(
        'x = pred[2] collective-permute(y), source_target_pairs={{0,1},{5,4}}'
    ) == [[0, 1], [5, 4]]
    assert hlo_facts.collective_groups('x = u32[8] all-reduce(y)') is None
    # replica_groups={} is XLA's ONE-group-of-everyone spelling — it must
    # fold into the all-participants None, never parse as "no groups" (an
    # empty list would read as no communication and slip the cross-tenant
    # budget).
    assert hlo_facts.collective_groups(
        'x = u32[8] all-reduce(y), replica_groups={}'
    ) is None

    block = device_program.AUDIT_TENANT_BLOCK
    assert not hlo_facts.groups_cross_blocks([[0, 1], [4, 5]], block)
    assert hlo_facts.groups_cross_blocks([[0, 4]], block)  # spans tenants
    assert hlo_facts.groups_cross_blocks(None, block)  # all-participants


def test_every_donation_is_aliased_or_waived():
    # Acceptance: every donate_argnums declaration is verified against the
    # compiled artifact; on this backend all of them land.
    facts = staticcheck.collect_facts()
    for name, entry in facts.items():
        donation = entry["donation"]
        assert donation["dropped"] == 0 or donation.get("waiver"), (
            name, donation,
        )
        if name != "sync":
            assert donation["aliased"] == donation["donated_leaves"] > 0, name


def test_update_hlo_lock_is_a_byte_identical_round_trip(
    tmp_path, monkeypatch, capsys
):
    # Same contract as the wire lock: regenerating over an unchanged tree
    # reproduces the committed file byte for byte. Redirected target so a
    # real divergence is caught, not silently overwritten.
    committed = (REPO / staticcheck.HLO_LOCK_REL).read_text()
    target = tmp_path / "hlo.lock.json"
    monkeypatch.setattr(device_program, "HLO_LOCK_REL", str(target))
    rc = staticcheck.main(["--update-hlo-lock"])
    assert rc == 0
    assert "wrote" in capsys.readouterr().out
    assert target.read_text() == committed


def test_tampered_lock_fails_the_gate_naming_the_delta(tmp_path, monkeypatch):
    # Drop the sharded wave's hot-loop all-reduce budget from a copy of the
    # lock: the live compiled program now exceeds it, and the finding names
    # the entrypoint, the location, and the payload delta.
    locked = json.loads((REPO / staticcheck.HLO_LOCK_REL).read_text())
    removed = locked["entrypoints"]["sharded_wave"]["collectives"].pop(
        "hot-loop/all-reduce"
    )
    target = tmp_path / "hlo.lock.json"
    target.write_text(json.dumps(locked))
    monkeypatch.setattr(device_program, "HLO_LOCK_REL", str(target))
    trees = [(None, rel) for rel in device_program.REGISTRY_SOURCES]
    findings = device_program.check_hlo_lock(trees)
    assert len(findings) == 1
    f = findings[0]
    assert f.check == "hlo-collective-budget"
    assert "sharded_wave" in f.message
    assert "HOT-LOOP" in f.message and "all-reduce" in f.message
    assert f"{removed['bytes']} bytes" in f.message


# ---------------------------------------------------------------------------
# The injected-defect acceptance case (corpus-compiled)
# ---------------------------------------------------------------------------


def test_injected_hot_loop_all_gather_fails_with_entrypoint_and_delta():
    findings = staticcheck.check_device_program(
        REPO / "rapid_tpu/models/_corpus.py",
        source=(CORPUS / "hot_loop_collective.py").read_text(),
    )
    assert len(findings) == 1
    f = findings[0]
    assert f.check == "hlo-collective-budget"
    assert "hot_loop_gather" in f.message  # the entrypoint
    assert "HOT-LOOP" in f.message and "hot-loop" in f.message  # location
    assert "all-gather" in f.message
    assert "256 bytes" in f.message and "class n" in f.message  # the delta
    assert "--update-hlo-lock" in f.message


def test_dropped_donation_reports_xla_reason():
    findings = staticcheck.check_device_program(
        REPO / "rapid_tpu/models/_corpus.py",
        source=(CORPUS / "donation_dropped.py").read_text(),
    )
    assert len(findings) == 1
    f = findings[0]
    assert f.check == "hlo-donation-dropped"
    assert "sum_donating" in f.message
    assert "1 of 1" in f.message
    # XLA's own reason rides the finding (captured from the compile-time
    # warning); degrade gracefully if a future jax stops warning.
    assert ("not usable" in f.message) or ("no XLA reason" in f.message)


# ---------------------------------------------------------------------------
# Retrace regression: recall with fresh same-shape inputs never recompiles
# ---------------------------------------------------------------------------


def _fresh_cluster(seed: int):
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    vc = VirtualCluster.create(
        56, n_slots=64, k=4, h=3, l=1, fd_threshold=2, cohorts=4,
        delivery_spread=1, seed=seed,
    )
    vc.assign_cohorts_roundrobin()
    vc.crash([1, 2])
    return vc


def _drive_all_entrypoints(seed: int) -> None:
    vc = _fresh_cluster(seed)
    vc.sync()
    vc.step()
    rounds, decided, _, _ = vc.run_to_decision(max_steps=32)
    assert decided, rounds
    vc2 = _fresh_cluster(seed + 100)
    vc2.run_until_membership(target=54, max_steps=64, max_cuts=4)


def test_entrypoints_compile_exactly_once_across_recalls():
    # The executable check behind the retrace-hazard lint: every library
    # entrypoint (step / run_to_decision / run_until_membership / sync)
    # driven twice with FRESH same-shape inputs reuses its executable —
    # zero new XLA compiles on the second pass, pinned via the
    # engine_telemetry compile counter. A weak-type or static-argnum
    # regression at any callsite shows up here as a recompile.
    from rapid_tpu.utils import engine_telemetry

    _drive_all_entrypoints(seed=0)  # warm: compiles (or persistent-cache hits)
    with engine_telemetry.CompileDelta() as delta:
        _drive_all_entrypoints(seed=1)
    assert delta.delta.get("compiles", 0) == 0, delta.delta
