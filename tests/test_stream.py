"""Streaming serving pipeline (rapid_tpu/serving): the streamed path must be
BIT-IDENTICAL to the batch path — the non-negotiable bar, the way
tests/test_tenancy.py pinned the fleet and tests/test_parallel_2d.py pinned
the 2-D mesh.

The pinned differential drives the SAME seeded Poisson churn schedule two
ways — wave by wave through ``StreamDriver`` (enqueue-only dispatches,
double-buffered deltas, sync only at fetch boundaries) and through the
pre-built batch seams (``crash``/``inject_join_wave`` + ``step``) — and
requires identical cuts, configuration ids, and final state pytrees, for
both the single-cluster and fleet paths. Only the synchronization structure
differs between the two drives; the compiled programs, inputs, and program
order are the same, so any divergence is a pipeline bug.

Budget (the PR-10 convention): the small-grid cluster+fleet differential is
the compile-bearing tier-1 representative; the larger grids (more waves,
more seeds, join-heavy schedules, wider fleets) ride the unfiltered
check.sh pass behind ``slow``.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.serving import (
    STREAMABLE_KINDS,
    FleetPoissonChurn,
    FleetWave,
    PoissonChurn,
    StreamDriver,
    StreamWave,
    waves_from_schedule,
)
from rapid_tpu.sim.faults import FaultEvent
from rapid_tpu.tenancy import TenantFleet


def _cluster(n=24, n_slots=40, seed=0):
    vc = VirtualCluster.create(
        n, n_slots=n_slots, k=3, h=3, l=1, cohorts=2, fd_threshold=2,
        seed=seed,
    )
    vc.assign_cohorts_roundrobin()
    return vc


def _fleet(b=3, n=16, seed0=10):
    clusters = []
    for i in range(b):
        vc = VirtualCluster.create(
            n, k=3, h=3, l=1, cohorts=2, fd_threshold=2, seed=seed0 + i
        )
        vc.assign_cohorts_roundrobin()
        clusters.append(vc)
    return TenantFleet.from_clusters(clusters)


def _trees_equal(a, b) -> bool:
    return bool(jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda x, y: bool((np.asarray(x) == np.asarray(y)).all()), a, b
    )))


def _batch_drive_cluster(vc, waves, rounds_per_wave):
    """The batch spelling of a stream schedule: pre-built per-wave deltas
    through the ordinary injection seams, per-round ``step`` dispatches,
    cut labels observed per round (the test_tenancy labeling)."""
    cuts, ids = [], []
    for wave in waves:
        if wave.crash:
            vc.crash(list(wave.crash))
        if wave.join:
            vc.inject_join_wave(list(wave.join))
        for _ in range(rounds_per_wave):
            was_alive = np.asarray(vc.state.alive)
            events = vc.step()
            if bool(events.decided):
                mask = np.asarray(events.winner_mask)
                cuts.append(frozenset(
                    (s, "down" if was_alive[s] else "up")
                    for s in np.nonzero(mask)[0].tolist()
                ))
                ids.append(vc.config_id)
    return cuts, ids


def _stream_seam_drive_cluster(vc, waves, rounds_per_wave):
    """The same schedule through the STREAM seams (fetch-free
    ``stream_step``, admissibility check skipped by the generator
    contract), retaining every round's device-resident events and fetching
    them only AFTER the drive — the pipeline discipline a caller that
    wants per-cut observability without per-round syncs would use."""
    retained = []
    for wave in waves:
        if wave.crash:
            vc.crash(list(wave.crash))
        if wave.join:
            vc.inject_join_wave(list(wave.join), check_admissible=False)
        for _ in range(rounds_per_wave):
            # Retain a device-side COPY: engine_step donates the state
            # pytree, so the live buffer would be deleted by the next
            # round. The copy is an enqueued dispatch — still no fetch.
            alive_before = jnp.copy(vc.state.alive)
            retained.append((alive_before, vc.stream_step()))
    cuts, ids = [], []
    epoch_seen = 0
    for alive_before, events in retained:
        if not bool(events.decided):  # post-drive fetch: the drive is done
            continue
        was_alive = np.asarray(alive_before)
        mask = np.asarray(events.winner_mask)
        cuts.append(frozenset(
            (s, "down" if was_alive[s] else "up")
            for s in np.nonzero(mask)[0].tolist()
        ))
        epoch_seen += 1
    return cuts, epoch_seen


def test_streamed_cluster_is_bit_identical_to_batch():
    """The tier-1 representative (grid variants ride ``slow``): one seeded
    Poisson schedule, three drives — StreamDriver, the stream seams with
    retained events, and the batch path — identical cuts, config ids, and
    final state+faults pytrees."""
    waves = PoissonChurn(24, 40, rate=1.0, seed=7).waves(6)

    streamed = _cluster()
    driver = StreamDriver(streamed, rounds_per_wave=4, depth=2)
    for wave in waves:
        driver.submit(wave)
    result = driver.drain()

    batch = _cluster()
    batch_cuts, batch_ids = _batch_drive_cluster(batch, waves, 4)
    assert batch_cuts, "schedule produced no cuts — the differential is vacuous"

    seams = _cluster()
    seam_cuts, seam_epochs = _stream_seam_drive_cluster(seams, waves, 4)

    # Final state AND faults pytrees: every leaf bit-identical.
    assert _trees_equal(streamed.state, batch.state)
    assert _trees_equal(streamed.faults, batch.faults)
    assert _trees_equal(seams.state, batch.state)
    # Config chain: the id is a hash chain over the whole cut history, so
    # equality here pins the entire view-change sequence.
    assert streamed.config_id == batch.config_id == seams.config_id
    assert streamed.config_epoch == batch.config_epoch
    # The cut sequences observed per round agree exactly.
    assert seam_cuts == batch_cuts
    assert seam_epochs == len(batch_cuts)
    # And the drained stream report agrees with the batch-side count.
    assert result.cuts == len(batch_cuts)
    assert result.waves == 6 and result.rounds == 24


def test_streamed_fleet_is_bit_identical_to_batch():
    """The fleet-path tier-1 representative: per-tenant Poisson crash
    streams through StreamDriver vs the batch fleet seams — identical
    per-tenant config ids, epochs, and final stacked pytrees."""
    waves = FleetPoissonChurn(3, 16, rate=0.7, seed=3).waves(5)

    streamed = _fleet()
    driver = StreamDriver(streamed, rounds_per_wave=3, depth=2)
    for wave in waves:
        driver.submit(wave)
    result = driver.drain()

    batch = _fleet()
    for wave in waves:
        if wave.crash:
            batch.stream_crash(wave.crash)
        for _ in range(3):
            batch.step()

    assert _trees_equal(streamed.state, batch.state)
    assert _trees_equal(streamed.faults, batch.faults)
    assert streamed.config_ids() == batch.config_ids()
    np.testing.assert_array_equal(
        streamed.config_epochs(), batch.config_epochs()
    )
    assert result.cuts == int(batch.config_epochs().sum())
    assert result.waves == 5


@pytest.mark.slow
def test_streamed_cluster_grid_bit_identical():
    """The larger differential grid: seeds x rates x pipeline depths,
    join-heavy and crash-heavy mixes. Rides the unfiltered check.sh pass;
    tier-1's wall budget keeps the single-point cluster differential
    (test_streamed_cluster_is_bit_identical_to_batch) as the acceptance
    pin."""
    for seed, rate, depth, join_fraction in [
        (1, 0.5, 1, 0.8), (2, 2.0, 3, 0.5), (3, 1.5, 2, 0.1),
    ]:
        waves = PoissonChurn(
            24, 40, rate=rate, seed=seed, join_fraction=join_fraction
        ).waves(8)
        streamed = _cluster()
        driver = StreamDriver(streamed, rounds_per_wave=4, depth=depth)
        for wave in waves:
            driver.submit(wave)
        driver.drain()
        batch = _cluster()
        _batch_drive_cluster(batch, waves, 4)
        label = f"seed={seed} rate={rate} depth={depth}"
        assert _trees_equal(streamed.state, batch.state), label
        assert streamed.config_id == batch.config_id, label


@pytest.mark.slow
def test_streamed_fleet_grid_bit_identical():
    """Wider fleet differential (more tenants, more waves, deeper
    pipeline). Rides the unfiltered check.sh pass; tier-1 keeps
    test_streamed_fleet_is_bit_identical_to_batch as the acceptance pin."""
    for seed, rate, depth in [(11, 0.3, 1), (12, 1.0, 4)]:
        waves = FleetPoissonChurn(3, 16, rate=rate, seed=seed).waves(10)
        streamed = _fleet()
        driver = StreamDriver(streamed, rounds_per_wave=3, depth=depth)
        for wave in waves:
            driver.submit(wave)
        driver.drain()
        batch = _fleet()
        for wave in waves:
            if wave.crash:
                batch.stream_crash(wave.crash)
            for _ in range(3):
                batch.step()
        label = f"seed={seed} rate={rate} depth={depth}"
        assert _trees_equal(streamed.state, batch.state), label
        assert streamed.config_ids() == batch.config_ids(), label


# ---------------------------------------------------------------------------
# The churn generators: pure functions of their seed
# ---------------------------------------------------------------------------


def test_poisson_churn_is_deterministic_per_seed():
    a = PoissonChurn(24, 40, rate=1.5, seed=42).waves(20)
    b = PoissonChurn(24, 40, rate=1.5, seed=42).waves(20)
    assert a == b
    c = PoissonChurn(24, 40, rate=1.5, seed=43).waves(20)
    assert a != c  # a different seed is a different schedule


def test_poisson_churn_respects_slot_lifecycle():
    # Fresh slots are never reused (the engine's UUID discipline — what
    # lets the stream skip the admissibility fetch) and crash victims are
    # only ever original members still standing.
    churn = PoissonChurn(24, 40, rate=3.0, seed=9)
    joined, crashed = set(), set()
    for wave in churn.waves(40):
        for slot in wave.join:
            assert slot not in joined and 24 <= slot < 40
            joined.add(slot)
        for slot in wave.crash:
            assert slot not in crashed and 0 <= slot < 24
            crashed.add(slot)


def test_fleet_poisson_churn_deterministic_and_in_range():
    a = FleetPoissonChurn(4, 16, rate=0.8, seed=5).waves(12)
    b = FleetPoissonChurn(4, 16, rate=0.8, seed=5).waves(12)
    assert a == b
    seen = set()
    for wave in a:
        for tenant, slot in wave.crash:
            assert 0 <= tenant < 4 and 0 <= slot < 16
            assert (tenant, slot) not in seen  # no double-crash per tenant
            seen.add((tenant, slot))


def test_generator_validation():
    with pytest.raises(ValueError):
        PoissonChurn(24, 40, rate=0.0)
    with pytest.raises(ValueError):
        PoissonChurn(24, 40, rate=1.0, join_fraction=1.5)
    with pytest.raises(ValueError):
        PoissonChurn(41, 40, rate=1.0)
    with pytest.raises(ValueError):
        FleetPoissonChurn(0, 16, rate=1.0)


def test_waves_from_schedule_speaks_the_sim_fault_vocabulary():
    events = [
        FaultEvent(kind="crash", slots=(1, 2)),
        FaultEvent(kind="join", slots=(24,)),
    ]
    waves = waves_from_schedule(events)
    assert waves == [StreamWave(crash=(1, 2)), StreamWave(join=(24,))]
    # Round trip: StreamWave.fault_events is the exact inverse.
    assert [e for w in waves for e in w.fault_events()] == events
    # settle=False events OVERLAP with their successor — they fold into
    # ONE wave (the whole delta applies before any engine round), never
    # serialize into convergence-separated waves the schedule forbade.
    overlapped = [
        FaultEvent(kind="crash", slots=(3,), settle=False),
        FaultEvent(kind="join", slots=(25,)),
        FaultEvent(kind="crash", slots=(4,)),
    ]
    merged = waves_from_schedule(overlapped)
    assert merged == [
        StreamWave(crash=(3,), join=(25,)),
        StreamWave(crash=(4,)),
    ]
    # ...and the round trip re-emits the overlap, not a settled rewrite.
    assert [e for w in merged for e in w.fault_events()] == overlapped
    # A trailing settle=False event still closes the final wave (it needs
    # its engine rounds even with nothing left to overlap with).
    assert waves_from_schedule(
        [FaultEvent(kind="crash", slots=(5,), settle=False)]
    ) == [StreamWave(crash=(5,))]
    # Everything the stream cannot represent is rejected loudly, never
    # silently dropped — a stream missing a partition event or a dwell is
    # a DIFFERENT scenario.
    with pytest.raises(ValueError, match="not streamable"):
        waves_from_schedule(
            [FaultEvent(kind="loss", slots=(), args={"permille": 50})]
        )
    with pytest.raises(ValueError, match="dwell_ms"):
        waves_from_schedule(
            [FaultEvent(kind="crash", slots=(1,), dwell_ms=250.0)]
        )
    assert STREAMABLE_KINDS == {"crash", "join"}


# ---------------------------------------------------------------------------
# Pipeline mechanics
# ---------------------------------------------------------------------------


def test_stream_driver_backpressure_bounds_waves_in_flight():
    vc = _cluster()
    driver = StreamDriver(vc, rounds_per_wave=2, depth=2)
    for wave in PoissonChurn(24, 40, rate=0.5, seed=1).waves(7):
        driver.submit(wave)
        assert len(driver._pending) <= 2  # the depth bound IS the backpressure
    result = driver.drain()
    assert driver.waves_completed == driver.waves_submitted == 7
    assert len(driver._pending) == 0
    assert result.overlap_efficiency is None or 0.0 <= result.overlap_efficiency <= 1.0


def test_stream_driver_rejects_mismatched_wave_types():
    vc = _cluster()
    cluster_driver = StreamDriver(vc)
    with pytest.raises(TypeError, match="FleetWave"):
        cluster_driver.submit(FleetWave(crash=((0, 1),)))
    fleet_driver = StreamDriver(_fleet())
    with pytest.raises(TypeError, match="StreamWave"):
        fleet_driver.submit(StreamWave(crash=(1,)))
    with pytest.raises(ValueError):
        StreamDriver(vc, rounds_per_wave=0)
    with pytest.raises(ValueError):
        StreamDriver(vc, depth=0)


def test_stream_metrics_and_snapshot_surface():
    vc = _cluster()
    driver = StreamDriver(vc, rounds_per_wave=2, depth=2)
    pre = driver.snapshot()
    # Pre-traffic snapshot: stable key set, None rates (exposition renders
    # NaN so the series set never changes shape).
    assert pre["waves_submitted"] == 0 and pre["view_changes_per_sec"] is None
    for wave in PoissonChurn(24, 40, rate=1.0, seed=2).waves(4):
        driver.submit(wave)
    result = driver.drain()
    snap = driver.snapshot()
    assert snap["waves_submitted"] == snap["waves_completed"] == 4
    assert snap["waves_in_flight"] == 0
    assert snap["view_changes_per_sec"] is not None
    assert vc.metrics.counters["engine_stream_waves"] == 4
    assert vc.metrics.counters["engine_stream_cuts"] == result.cuts
    # The alert->commit latencies land in the shared bounded instrument.
    assert vc.metrics.timings["engine_stream_alert_to_commit"].count == 4
    # The pipeline's dispatch accounting: enqueues under stream_enqueue,
    # sync boundaries under stream_fetch — nothing else.
    family = vc.metrics.phase_timings["engine_dispatch"]
    assert family["stream_enqueue"].count == 8  # 4 waves x 2 rounds
    assert family["stream_fetch"].count >= 1  # the drain boundary
    # The whole snapshot is scrape-ready (clustertop / --metrics-dump).
    json.dumps(vc.telemetry_snapshot())


def test_stream_join_wave_skips_admissibility_fetch():
    # The generator owns the slot bookkeeping, so the streamed join must
    # not pay the [j]-bool device->host fetch (it would stall every
    # enqueued wave behind it); the batch spelling keeps the check.
    vc = _cluster()
    d2h0 = vc.metrics.counters["engine_d2h_bytes"]
    vc.inject_join_wave([30, 31], check_admissible=False)
    assert vc.metrics.counters["engine_d2h_bytes"] == d2h0
    vc2 = _cluster()
    d2h0 = vc2.metrics.counters["engine_d2h_bytes"]
    vc2.inject_join_wave([30, 31])
    assert vc2.metrics.counters["engine_d2h_bytes"] == d2h0 + 2
    with pytest.raises(ValueError, match="not admissible"):
        vc2.inject_join_wave([30])  # already pending: the check still bites


def test_stream_driver_enforces_admissibility_host_side():
    # The driver mirrors the slot lifecycle on host (ONE pre-stream fetch,
    # pure bookkeeping per wave): a schedule-derived join of a reused slot
    # raises the SAME error the batch path fetches [j] bools to produce —
    # for every wave source, not just PoissonChurn's fresh-slot contract.
    vc = _cluster()
    driver = StreamDriver(vc, rounds_per_wave=1, depth=2)
    with pytest.raises(ValueError, match="not admissible"):
        driver.submit(StreamWave(join=(3,)))  # already a member
    driver.submit(StreamWave(crash=(5,), join=(30,)))
    with pytest.raises(ValueError, match="not admissible"):
        driver.submit(StreamWave(join=(30,)))  # pending from the last wave
    with pytest.raises(ValueError, match="not admissible"):
        driver.submit(StreamWave(join=(5,)))  # crashed slots never rejoin
    driver.drain()


def test_empty_wave_has_no_schedule_spelling():
    # Poisson pacing waves (k=0 draws) cannot serialize: the schedule
    # grammar forbids membership events without slots, and dropping the
    # wave would replay fewer engine rounds than the stream ran. Loud,
    # never silent (the waves_from_schedule discipline, in reverse).
    with pytest.raises(ValueError, match="empty wave"):
        StreamWave().fault_events()


def test_drain_rate_math_is_well_defined_for_degenerate_streams():
    """ISSUE 15 satellite: zero-wave and zero-elapsed drains report rate
    0.0 and a well-defined StreamResult — no div-by-~0 inf/NaN can leak
    into bench JSON, and 0.0 (drained, nothing to rate) stays distinct
    from the pre-drain snapshot's None (not yet drained)."""
    import math

    # Zero waves: nothing ever submitted, wall is exactly 0.
    vc = _cluster()
    result = StreamDriver(vc, rounds_per_wave=2, depth=2).drain()
    assert result.waves == 0 and result.rounds == 0 and result.cuts == 0
    assert result.wall_ms == 0.0
    assert result.view_changes_per_sec == 0.0
    assert result.p99_alert_to_commit_ms is None
    assert result.overlap_efficiency is None  # unmeasurable, not fake
    for value in result:
        assert not (isinstance(value, float) and (
            math.isnan(value) or math.isinf(value)
        ))
    json.dumps(vc.telemetry_snapshot())

    # Zero elapsed: a frozen injected clock makes wall_ms exactly 0 even
    # WITH traffic — the rate must still be 0.0, never cuts/0 = inf.
    vc2 = _cluster()
    frozen = StreamDriver(vc2, rounds_per_wave=2, depth=2, clock=lambda: 5.0)
    for wave in PoissonChurn(24, 40, rate=1.0, seed=2).waves(3):
        frozen.submit(wave)
    result2 = frozen.drain()
    assert result2.wall_ms == 0.0
    assert result2.view_changes_per_sec == 0.0
    assert result2.overlap_efficiency is None
    for value in result2:
        assert not (isinstance(value, float) and (
            math.isnan(value) or math.isinf(value)
        ))


def test_fleet_stream_crash_bounds_checked():
    fleet = _fleet()
    with pytest.raises(IndexError):
        fleet.stream_crash([(3, 0)])  # tenant out of range
    with pytest.raises(IndexError):
        fleet.stream_crash([(0, 16)])  # slot out of range
