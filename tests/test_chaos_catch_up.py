"""Multi-cycle partition/heal chaos soak for the config catch-up path.

The fallback-forcing oracle case (test_oracle_parity.py) proves ONE cycle:
ingress-blocked observers miss a decision and pull their way back. This
soak generalizes it: over several cycles with seeded random blocked sets,
the cluster keeps deciding membership changes (each forced through the
classic fallback — the blocked set is sized to hold the fast round below
quorum), and the blocked members keep re-joining the new configuration
through the partition via reliable-path config pulls. Invariants per
cycle: every live node (blocked included) reaches the identical view, no
node is ever kicked, and the configuration chain advances monotonically
(identifier history grows on joins) across MULTIPLE missed decisions per
node — exercising the known-config-id history, the futile-pull memory, and
repeated catch-up installs on the same service instance.
"""

import asyncio
import functools
import random

import pytest

from rapid_tpu.types import Endpoint

from test_oracle_parity import _HostHarness


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=300)

        asyncio.run(with_timeout())

    return wrapper


N0 = 12
CYCLES = 4
BLOCKED_PER_CYCLE = 3  # voters 12-1-3 < fast quorum 10: classic every cycle


@pytest.mark.parametrize("seed", [21, 22])
@async_test
async def test_repeated_partitions_heal_by_catch_up(seed):
    rng = random.Random(seed)
    endpoints = [
        Endpoint(f"10.6.{seed}.{i}", 7600 + i) for i in range(N0 + CYCLES)
    ]
    h = _HostHarness(endpoints)
    # Fast idle heartbeat: a blocked member that is NOT an observer of the
    # change has zero local evidence and zero inbound traffic — the
    # unconditional anti-entropy pull is the only channel that reaches it
    # through a one-way partition (settings.py rationale).
    h.settings.config_sync_idle_interval_ms = 2_000
    await h.bootstrap(N0)
    kicked = []
    for cluster in h.clusters.values():
        from rapid_tpu.protocol.events import ClusterEvents

        cluster.register_subscription(ClusterEvents.KICKED, kicked.append)

    members = N0
    next_join = N0
    total_catch_ups_before = 0
    for cycle in range(CYCLES):
        # Random blocked set: live members, never the seed, never this
        # cycle's crash victim.
        live = sorted(h.live_ids - {0})
        blocked = rng.sample(live, BLOCKED_PER_CYCLE)
        victim = rng.choice([s for s in live if s not in blocked])
        for b in blocked:
            for other in h.clusters:
                if other != b:
                    h.network.blackholed_links.add(
                        (h.endpoints[other], h.endpoints[b])
                    )

        # Alternate crash and join cycles so identifier history both grows
        # and the endpoint set both shrinks and grows across the chain.
        if cycle % 2 == 0:
            h.crash([victim])
            members -= 1
        else:
            await h.join_one(next_join)
            next_join += 1
            members += 1

        # Blocked members must reach the new configuration THROUGH the
        # partition (their pulls ride request/response; ingress of pushed
        # traffic stays dead until the heal below).
        await h.converge_members(members, budget_ms=90_000)

        h.network.blackholed_links.clear()
        await h.converge_members(members)
        assert not kicked, f"cycle {cycle}: healthy member kicked: {kicked}"

        total_catch_ups = sum(
            h.clusters[i].service.metrics.counters["config_catch_ups"]
            for i in h.live_ids
        )
        assert total_catch_ups >= total_catch_ups_before
        total_catch_ups_before = total_catch_ups

    # The soak must have exercised the catch-up path, not converged by luck.
    assert total_catch_ups_before >= CYCLES - 1, (
        f"expected repeated catch-ups across {CYCLES} cycles, "
        f"saw {total_catch_ups_before}"
    )
    final = await h.shutdown()
    assert len(final) == members
