"""Multi-cycle partition/heal chaos soak for the config catch-up path.

The fallback-forcing oracle case (test_oracle_parity.py) proves ONE cycle:
ingress-blocked observers miss a decision and pull their way back. This
soak generalizes it: over several cycles with seeded random blocked sets,
the cluster keeps deciding membership changes (each forced through the
classic fallback — the blocked set is sized to hold the fast round below
quorum), and the blocked members keep re-joining the new configuration
through the partition via reliable-path config pulls. Invariants per
cycle: every live node (blocked included) reaches the identical view, no
node is ever kicked, and the configuration chain advances monotonically
(identifier history grows on joins) across MULTIPLE missed decisions per
node — exercising the known-config-id history, the futile-pull memory, and
repeated catch-up installs on the same service instance.

The scaffolding and fault primitives are the chaos subsystem's
(rapid_tpu/sim: SimHarness ``ingress_block``/``heal_partitions`` over the
in-process seams, config-chain capture, ``sim_settings``); the cycle loop
stays bespoke because each cycle's blocked set is drawn from LIVE state —
a dynamic schedule the declarative model intentionally does not express.
"""

import asyncio
import functools
import random

import pytest

from rapid_tpu.sim.scenario import SimHarness, sim_settings
from rapid_tpu.types import Endpoint


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=300)

        asyncio.run(with_timeout())

    return wrapper


N0 = 12
CYCLES = 4
BLOCKED_PER_CYCLE = 3  # voters 12-1-3 < fast quorum 10: classic every cycle


@pytest.mark.parametrize("seed", [21, 22])
@async_test
async def test_repeated_partitions_heal_by_catch_up(seed):
    rng = random.Random(seed)
    endpoints = [
        Endpoint(f"10.6.{seed}.{i}", 7600 + i) for i in range(N0 + CYCLES)
    ]
    # sim_settings: the fast idle heartbeat — a blocked member that is NOT
    # an observer of the change has zero local evidence and zero inbound
    # traffic; the unconditional anti-entropy pull is the only channel that
    # reaches it through a one-way partition (settings.py rationale).
    h = SimHarness(endpoints, settings=sim_settings(), id_seed=seed)
    await h.bootstrap(N0)

    members = N0
    next_join = N0
    total_catch_ups_before = 0
    for cycle in range(CYCLES):
        # Random blocked set: live members, never the seed, never this
        # cycle's crash victim.
        live = sorted(h.live_ids - {0})
        blocked = rng.sample(live, BLOCKED_PER_CYCLE)
        victim = rng.choice([s for s in live if s not in blocked])
        # Ingress blocked from every EXISTING node (not from this cycle's
        # fresh joiner: a new process's packets ride new flows the stale
        # partition rule never matched — and an admission needs the blocked
        # gatekeepers to hear the joiner's phase-2 messages; blocking those
        # too is the wedge shape test_sim_fuzz.py pins, not this soak).
        for b in blocked:
            for other in h.clusters:
                if other != b:
                    h.block_link(other, b)

        # Alternate crash and join cycles so identifier history both grows
        # and the endpoint set both shrinks and grows across the chain.
        if cycle % 2 == 0:
            h.crash([victim])
            members -= 1
        else:
            await h.join_one(next_join)
            next_join += 1
            members += 1

        # Blocked members must reach the new configuration THROUGH the
        # partition (their pulls ride request/response; ingress of pushed
        # traffic stays dead until the heal below).
        await h.converge_members(members, budget_ms=90_000)

        h.heal_partitions()
        await h.converge_members(members)
        assert not h.kicked, f"cycle {cycle}: healthy member kicked: {h.kicked}"

        total_catch_ups = sum(
            h.clusters[i].service.metrics.counters["config_catch_ups"]
            for i in h.live_ids
        )
        assert total_catch_ups >= total_catch_ups_before
        total_catch_ups_before = total_catch_ups

    # The soak must have exercised the catch-up path, not converged by luck.
    assert total_catch_ups_before >= CYCLES - 1, (
        f"expected repeated catch-ups across {CYCLES} cycles, "
        f"saw {total_catch_ups_before}"
    )
    # Chain monotonicity across every missed decision: the harness captured
    # each node's delivered configuration history; every live node's history
    # must be a strictly-ordered subsequence of the never-faulted seed's
    # chain (catch-up may SKIP configurations, never fork or regress).
    reference = {cid: i for i, (cid, _) in enumerate(h.configs[0])}
    for slot in sorted(h.live_ids):
        positions = [reference.get(cid) for cid, _ in h.configs[slot]]
        assert None not in positions, (
            f"slot {slot}: delivered a configuration the seed's chain never "
            f"had — a fork"
        )
        assert positions == sorted(set(positions)), (
            f"slot {slot}: configuration history not monotone in the seed's "
            f"chain: {positions}"
        )
    final = await h.shutdown()
    assert len(final) == members
