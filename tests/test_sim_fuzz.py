"""Schedule fuzzing and shrinking: the regression half of the chaos
subsystem.

The tier-1 pieces prove the search machinery BITES: a known
oracle-violating schedule (a full symmetric partition spanning a crash
decision, never healed — the isolated member goes permanently stale)
shrinks to a minimal repro that still fails with the same violations, and
a written repro file replays to the identical violation set. The broad
fuzz sweep over many random seeds is marked ``slow`` (excluded from
tier-1; run it with ``-m slow`` or ``tools/chaosrun.py fuzz``)."""

import pytest

from rapid_tpu.sim.faults import FaultEvent, FaultSchedule
from rapid_tpu.sim.fuzz import (
    fuzz,
    random_schedule,
    replay,
    run_schedule,
    shrink,
    write_repro,
)
from rapid_tpu.sim.oracles import check_all


def _known_violating_schedule() -> FaultSchedule:
    """A schedule that genuinely breaks the invariants: slot 3 is fully
    isolated (symmetric partition, below the detection threshold so it is
    never evicted) across a crash decision and the partition never heals —
    slot 3 can neither hear the decision nor pull it, so the cluster never
    re-converges. The loss and join events are noise the shrinker must
    strip. Budgets are tight: every shrink attempt re-runs the scenario,
    and a wedged phase burns its whole simulated budget."""
    return FaultSchedule(
        n0=8, n_slots=12, seed=5, name="violating/partition-no-heal",
        phase_budget_ms=20_000, converge_budget_ms=10_000,
        events=[
            FaultEvent("loss", args={"permille": 30}),
            FaultEvent("join", (8,), dwell_ms=500),
            FaultEvent("partition", (3,), dwell_ms=500),
            FaultEvent("crash", (2,), dwell_ms=500),
        ],
    )


def test_shrinker_reduces_known_violation_to_minimal_repro(tmp_path):
    schedule = _known_violating_schedule()
    result = run_schedule(schedule)
    violations = check_all(result, differential=False)
    names = {v.oracle for v in violations}
    assert "bounded-convergence" in names  # the violation is real

    minimal, min_violations, runs = shrink(schedule, violations)
    assert runs > 0
    # Greedy floor: nothing survives but the partition and the decision it
    # conceals — the noise events (loss, join) are gone, dwells zeroed.
    assert [e.kind for e in minimal.events] == ["partition", "crash"]
    assert all(e.dwell_ms == 0 for e in minimal.events)
    assert len(minimal.events[0].slots) == 1
    # The minimal repro still fails with (at least) the original violations.
    assert names <= {v.oracle for v in min_violations}

    # The written repro replays to the IDENTICAL violation set.
    min_result = run_schedule(minimal)
    repro_dir = write_repro(min_result, min_violations, tmp_path)
    assert (repro_dir / "schedule.json").exists()
    assert (repro_dir / "violations.txt").read_text().strip()
    replayed_result, replayed_violations = replay(repro_dir)
    assert sorted(map(str, replayed_violations)) == sorted(
        map(str, check_all(min_result))
    )
    assert replayed_result.cuts == min_result.cuts


def test_shrink_refuses_a_passing_schedule():
    schedule = random_schedule(0)
    with pytest.raises(ValueError, match="nothing to shrink"):
        shrink(schedule, [])


@pytest.mark.slow
def test_fuzz_writes_repro_for_violating_seed(tmp_path, monkeypatch):
    # Drive the fuzz loop's failure path deterministically: patch the
    # generator to return the known-violating schedule, and verify the loop
    # shrinks it and writes a replayable repro directory.
    # Rides the unfiltered check.sh pass (~10 s wall: a full fuzz round +
    # shrink + replay); the shrinker-regression test above keeps the
    # shrink/replay contract in tier-1.
    import rapid_tpu.sim.fuzz as simfuzz

    monkeypatch.setattr(
        simfuzz, "random_schedule", lambda seed: _known_violating_schedule()
    )
    (summary,) = fuzz([42], out_dir=tmp_path)
    assert summary["violations"]
    assert summary["shrunk_events"] < summary["events"]
    repro = tmp_path / "seed42"
    assert (repro / "schedule.json").exists()
    _, replayed = replay(repro)
    assert replayed  # the repro still fails after the round trip


@pytest.mark.slow
def test_fuzz_sweep_random_schedules_are_clean():
    # The actual search: random schedules across a seed range must uphold
    # every oracle (a failure here is a protocol bug — the summaries carry
    # the shrunk repro). Excluded from tier-1 by the slow marker; the
    # pinned-family coverage lives in test_sim_smoke.py.
    summaries = fuzz(range(12), out_dir=None, shrink_failures=False)
    failing = [s for s in summaries if s["violations"]]
    assert not failing, "\n".join(
        f"seed {s['seed']}: {s['violations']}" for s in failing
    )


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
