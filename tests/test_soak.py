"""Soak tests: many consecutive view changes exercise per-configuration state
resets (cut detector, votes, FD counters, classic acceptor state) across
epochs — the class of bug that single-view tests can't see."""

import asyncio
import random

import numpy as np
import pytest

from rapid_tpu.models.virtual_cluster import VirtualCluster


def test_engine_churn_soak_ten_epochs():
    # Alternating crash waves and join waves over 10 configurations; every
    # epoch must converge and membership accounting must stay exact.
    n_slots = 640
    vc = VirtualCluster.create(500, n_slots=n_slots, fd_threshold=2, seed=20)
    rng = np.random.default_rng(20)
    expected = 500
    dead: set = set()
    next_join = 500

    for epoch in range(10):
        if epoch % 2 == 0:
            # Crash 1-2% of current members.
            alive_slots = np.nonzero(vc.alive_mask)[0]
            victims = rng.choice(alive_slots, size=max(2, expected // 60), replace=False)
            vc.crash(victims)
            dead.update(int(v) for v in victims)
            expected -= len(victims)
        else:
            # Join a small wave into fresh slots.
            wave = list(range(next_join, min(next_join + 12, n_slots)))
            if not wave:
                continue
            vc.inject_join_wave(wave)
            next_join += len(wave)
            expected += len(wave)

        rounds, events = vc.run_until_converged(max_steps=32)
        assert events is not None, f"epoch {epoch} did not converge"
        assert vc.config_epoch == epoch + 1
        assert vc.membership_size == expected, f"epoch {epoch}"
        alive = vc.alive_mask
        assert not any(alive[d] for d in dead), "a crashed slot came back"

    # State sanity after 10 epochs: nothing left armed.
    assert int(vc.state.rounds_undecided) == 0
    assert not bool(np.asarray(vc.state.announced).any())
    assert not bool(np.asarray(vc.state.vote_valid).any())


def test_host_rejoin_cycles():
    # A node crashes, is evicted, and rejoins — three times over, with the
    # same address each time (ClusterTest.java rejoin loops).
    from rapid_tpu.messaging.inprocess import InProcessNetwork
    from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
    from rapid_tpu.protocol.cluster import Cluster
    from rapid_tpu.settings import Settings
    from rapid_tpu.types import Endpoint

    async def scenario():
        settings = Settings()
        settings.batching_window_ms = 20
        settings.failure_detector_interval_ms = 50
        network = InProcessNetwork()
        fd = StaticFailureDetectorFactory()

        def ep(i):
            return Endpoint("127.0.0.1", 42100 + i)

        clusters = [await Cluster.start(ep(0), settings=settings, network=network,
                                        fd_factory=fd, rng=random.Random(0))]
        for i in range(1, 5):
            clusters.append(await Cluster.join(ep(0), ep(i), settings=settings,
                                               network=network, fd_factory=fd,
                                               rng=random.Random(i)))

        async def converged(cs, size):
            for _ in range(600):
                if all(c.membership_size == size for c in cs) and (
                    len({tuple(c.membership) for c in cs}) == 1
                ):
                    return True
                await asyncio.sleep(0.02)
            return False

        assert await converged(clusters, 5)
        bouncer_addr = ep(4)
        for cycle in range(3):
            bouncer = next(c for c in clusters if c.listen_address == bouncer_addr)
            network.blackholed.add(bouncer_addr)
            fd.add_failed_nodes([bouncer_addr])
            clusters.remove(bouncer)
            assert await converged(clusters, 4), f"evict cycle {cycle}"
            await bouncer.shutdown()

            network.blackholed.discard(bouncer_addr)
            fd.blacklist.discard(bouncer_addr)
            rejoined = await Cluster.join(ep(0), bouncer_addr, settings=settings,
                                          network=network, fd_factory=fd,
                                          rng=random.Random(100 + cycle))
            clusters.append(rejoined)
            assert await converged(clusters, 5), f"rejoin cycle {cycle}"

        for c in clusters:
            await c.shutdown()

    asyncio.run(asyncio.wait_for(scenario(), timeout=90))


def test_engine_mixed_lifecycle_soak_with_jitter_and_windowed_fd():
    # Long-haul: 16 configurations of mixed churn (crashes, joins, graceful
    # leaves) under delivery jitter, many cohorts, racing coordinators, and
    # the windowed FD policy — the cross-configuration state carried between
    # epochs (retired lanes, pending joiners, fd histories, delivery stamps)
    # must stay exact the whole way.
    n_slots = 512
    vc = VirtualCluster.create(
        360, n_slots=n_slots, fd_threshold=3, seed=30, cohorts=32,
        delivery_spread=2, concurrent_coordinators=2, fd_window=8,
    )
    vc.assign_cohorts_roundrobin()
    rng = np.random.default_rng(30)
    expected = 360
    gone: set = set()
    next_join = 360

    for epoch in range(16):
        kind = epoch % 4
        if kind in (0, 2):
            alive_slots = np.nonzero(vc.alive_mask)[0]
            victims = rng.choice(alive_slots, size=4, replace=False)
            if kind == 0:
                vc.crash(victims)
            else:
                vc.initiate_leave(victims)
            gone.update(int(v) for v in victims)
            expected -= len(victims)
        else:
            wave = list(range(next_join, min(next_join + 8, n_slots)))
            if not wave:
                continue
            vc.inject_join_wave(wave)
            next_join += len(wave)
            expected += len(wave)

        rounds, events = vc.run_until_converged(max_steps=64)
        assert events is not None, f"epoch {epoch} did not converge"
        assert vc.membership_size == expected, f"epoch {epoch}"
        alive = vc.alive_mask
        assert not any(alive[g] for g in gone), "a departed slot came back"
        # Departed lanes are retired; none is ever admissible again.
        retired = np.asarray(vc.state.retired)
        assert all(retired[g] for g in gone)

    assert int(vc.state.rounds_undecided) == 0
    assert not bool(np.asarray(vc.state.announced).any())


@pytest.mark.slow
def test_fused_wave_churn_soak_twenty_epochs():
    # Rides the unfiltered check.sh pass (~11 s wall). Tier-1 keeps the
    # per-step soak above plus the fused-wave multi-cut representative
    # test_engine.py::test_run_until_membership_matches_sequential_decisions.
    # The whole-wave dispatch across MANY configurations: per-configuration
    # state resets (cut detector, votes, FD counters, classic acceptors)
    # must survive repeated on-device view-change application inside the
    # fused loop, not just the per-step driver the soak above exercises.
    n_slots = 1100
    vc = VirtualCluster.create(800, n_slots=n_slots, fd_threshold=2, seed=31,
                               cohorts=16, delivery_spread=2)
    vc.assign_cohorts_roundrobin()
    rng = np.random.default_rng(31)
    expected, dead, next_join = 800, set(), 800
    for epoch in range(20):
        if epoch % 2 == 0:
            alive_slots = np.nonzero(vc.alive_mask)[0]
            victims = rng.choice(alive_slots, size=max(2, expected // 80),
                                 replace=False)
            vc.crash(victims)
            dead.update(int(v) for v in victims)
            expected -= len(victims)
        else:
            wave = list(range(next_join, min(next_join + 10, n_slots)))
            if not wave:
                continue  # no churn injected -> min_cuts=1 could never resolve
            vc.inject_join_wave(wave)
            next_join += len(wave)
            expected += len(wave)
        rounds, cuts, resolved, sizes = vc.run_until_membership(
            expected, min_cuts=1, max_steps=512
        )
        assert resolved, (epoch, rounds, cuts, sizes, vc.membership_size)
        assert vc.membership_size == expected
        assert sizes[-1] == expected  # the instrument agrees with the fetch
        assert not vc.alive_mask[sorted(dead)].any()
