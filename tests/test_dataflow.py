"""Proof + unit gate for the jaxpr dataflow provenance family.

The expensive half traces the REAL registry once per session (compile
free — ``jitted.trace``) and pins the ISSUE-19 acceptance surface: the
observer-silence and tenant-isolation proofs hold over every registered
entrypoint, the sparse-opportunity map explains >= 90% of the frozen
quiescent payload bytes, and the committed ``dataflow.lock.json``
round-trips byte-identically. The cheap half runs synthetic jaxprs
through the taint interpreter — most importantly the scan-carry /
donated-buffer aliasing cases where a union-carry interpreter would
fabricate influence edges the per-slot fixpoint must not.
"""

from __future__ import annotations

import ast
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import staticcheck  # noqa: E402
from analysis import dataflow, device_program  # noqa: E402
from analysis.core import Finding  # noqa: E402


def _registry_trees():
    """The minimal (tree, rel) set that opens the presence gate."""
    return [(ast.parse(""), src) for src in device_program.REGISTRY_SOURCES]


# ---------------------------------------------------------------------------
# The proofs over the real registry (session-cached trace)
# ---------------------------------------------------------------------------


def test_head_proofs_hold_over_every_registered_entrypoint():
    payload, findings = staticcheck.collect_dataflow()
    assert not findings, "\n".join(str(f) for f in findings)
    registry = set(device_program._build_registry())
    assert set(payload["entrypoints"]) == registry | {"fleet_step"}
    for name, entry in payload["entrypoints"].items():
        assert entry["observer_silent"] is True, name
    for name, proof in payload["tenant_isolation"].items():
        assert proof["proven"] is True, name
        assert proof["mixed_outputs"] == [], name
        assert proof["axis_rule_fallbacks"] == [], name


def test_opportunity_map_explains_the_frozen_quiescent_bytes():
    payload, _ = staticcheck.collect_dataflow()
    opp = payload["opportunity_map"]
    frozen = json.loads(
        (staticcheck.core.REPO / staticcheck.COST_LOCK_REL).read_text()
    )
    assert opp["total_collective_payload_bytes"] == (
        frozen["quiescent_round_cost"]["collective_payload_bytes"]
    )
    assert opp["coverage_pct"] >= 90.0
    # Every claimed bucket names the mask lane(s) gating its dense ops —
    # that attribution is what makes the map a work-list, not a listing.
    for bucket in opp["dense_gated"]:
        for op in bucket["dense_ops"]:
            assert op["gated_by"], (bucket, op)


def test_carry_only_lanes_reconcile_with_the_deadcode_collector():
    # The two liveness families must never disagree: every lane the jaxpr
    # says is carry-only is host-fetched by name (attribute reads,
    # getattr strings, f-string fields — the deadcode family's collector),
    # which is exactly why no dataflow-dead-lane finding fires on HEAD.
    payload, findings = staticcheck.collect_dataflow()
    referenced = dataflow._tree_reference_names()
    for lane in payload["carry_only_lanes"]:
        assert dataflow._field_of(lane) in referenced, lane
    assert not [f for f in findings if f.check == "dataflow-dead-lane"]


def test_committed_lock_matches_the_live_trace():
    assert staticcheck.check_dataflow_lock(_registry_trees()) == []


# ---------------------------------------------------------------------------
# Lock machinery
# ---------------------------------------------------------------------------


def test_update_dataflow_lock_is_a_deterministic_round_trip(
    tmp_path, monkeypatch, capsys
):
    # Regenerating over an unchanged tree produces the byte-identical
    # lock, into a REDIRECTED path so the committed file is never
    # silently overwritten (same discipline as the wire-lock round trip).
    committed = (
        staticcheck.core.REPO / staticcheck.DATAFLOW_LOCK_REL
    ).read_text()
    target = tmp_path / "dataflow.lock.json"
    monkeypatch.setattr(dataflow, "DATAFLOW_LOCK_REL", str(target))
    rc = staticcheck.main(["--update-dataflow-lock"])
    assert rc == 0
    assert "wrote" in capsys.readouterr().out
    assert target.read_text() == committed


def test_update_refuses_while_any_proof_fails(tmp_path, monkeypatch):
    leak = Finding(
        "tools/analysis/dataflow.lock.json", 1, "dataflow-observer-effect",
        "observer lane telem.tl_enq influences subject lane state.cuts",
    )
    monkeypatch.setattr(
        dataflow, "collect_dataflow", lambda force=False: ({}, [leak])
    )
    target = tmp_path / "dataflow.lock.json"
    monkeypatch.setattr(dataflow, "DATAFLOW_LOCK_REL", str(target))
    findings, lock_path = dataflow.update_dataflow_lock()
    assert lock_path is None and not target.exists()
    assert [f.check for f in findings] == ["dataflow-observer-effect"]
    assert findings[0].message.startswith("refusing to freeze: ")


def test_lock_drift_is_reported_per_block(tmp_path, monkeypatch):
    tampered = json.loads(
        (staticcheck.core.REPO / staticcheck.DATAFLOW_LOCK_REL).read_text()
    )
    tampered["carry_only_lanes"] = ["state.no_such_lane"]
    target = tmp_path / "dataflow.lock.json"
    target.write_text(json.dumps(tampered, indent=2, sort_keys=True) + "\n")
    monkeypatch.setattr(dataflow, "DATAFLOW_LOCK_REL", str(target))
    findings = staticcheck.check_dataflow_lock(_registry_trees())
    assert [f.check for f in findings] == ["dataflow-lock-drift"]
    assert "carry_only_lanes" in findings[0].message


def test_presence_gate_skips_retargeted_trees():
    # A tree without the engine sources (a tmp_path unit-test tree) must
    # never pay a registry trace or compare against the lock.
    trees = [(ast.parse(""), "some/other/module.py")]
    assert staticcheck.check_dataflow_lock(trees) == []


def test_coverage_floor_and_two_lock_total_are_enforced():
    opp = {
        "total_collective_payload_bytes": 100,
        "coverage_pct": 50.0,
        "unclaimed": [
            {"location": "cond", "source": "reduction", "bytes": 50},
        ],
    }
    findings = dataflow._coverage_findings(opp, ("probe", 1))
    messages = [f.message for f in findings]
    assert any("does not match the cost lock" in m for m in messages)
    assert any("floor 90%" in m for m in messages)
    assert all(f.check == "dataflow-dense-op" for f in findings)


# ---------------------------------------------------------------------------
# Taint interpreter: carry aliasing must not fabricate influence edges
# ---------------------------------------------------------------------------


def _out_taints(jitted, args):
    entry = dataflow._trace_entry("probe", {"jit": jitted, "args": args})
    n = len(entry["in_labels"])
    return dataflow._taint_closed(
        entry["closed"], [frozenset([i]) for i in range(n)]
    )


def test_scan_carry_slots_stay_separate():
    # carry = (a, b); the body never mixes them. A union-carry
    # interpreter (one taint set for the whole carry) would report a's
    # lineage in b_final and vice versa — the per-slot fixpoint must not.
    def step(carry, x):
        a, b = carry
        return (a + 1.0, b * 2.0), b + x

    jitted = jax.jit(lambda a, b, xs: jax.lax.scan(step, (a, b), xs))
    args = (
        jnp.float32(0.0),
        jnp.float32(1.0),
        jnp.zeros((4,), jnp.float32),
    )
    a_final, b_final, ys = _out_taints(jitted, args)
    assert a_final == frozenset([0])
    assert b_final == frozenset([1])
    assert ys == frozenset([1, 2])


def test_donated_while_carry_reuse_keeps_slots_apart():
    # Donated buffers mean the compiled program reuses the carry slots in
    # place — at the jaxpr level the slots are still distinct variables,
    # and the fixpoint must keep them apart. The loop counter drives the
    # predicate, so BOTH data slots legitimately inherit its taint
    # (iteration count is influence); the data slots must not inherit
    # each other's.
    def loop(state):
        def cond(s):
            return s[0] < 3

        def body(s):
            return (s[0] + 1, s[1] + 1.0, s[2] * 2.0)

        return jax.lax.while_loop(cond, body, state)

    jitted = jax.jit(loop, donate_argnums=(0,))
    args = ((jnp.int32(0), jnp.float32(0.0), jnp.float32(1.0)),)
    counter, a_final, b_final = _out_taints(jitted, args)
    assert counter == frozenset([0])
    assert a_final == frozenset([0, 1])
    assert b_final == frozenset([0, 2])


# ---------------------------------------------------------------------------
# Corpus mode plumbing (the probes themselves live in the lint corpus)
# ---------------------------------------------------------------------------


def test_corpus_mode_skips_files_without_the_marker(tmp_path):
    probe = tmp_path / "plain.py"
    probe.write_text("X = 1\n")
    assert staticcheck.check_dataflow(probe) == []


def test_corpus_mode_reports_a_broken_probe_as_a_finding(tmp_path):
    probe = tmp_path / "broken_probe.py"
    probe.write_text(
        "DATAFLOW_AUDIT_PROGRAMS = {}\nraise RuntimeError('boom')\n"
    )
    findings = staticcheck.check_dataflow(probe)
    assert [f.check for f in findings] == ["dataflow-lock-drift"]
    assert "failed to execute" in findings[0].message
