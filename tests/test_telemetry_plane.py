"""Device telemetry plane: telemetry=1 must be PURE observation.

The non-negotiable bar (the ISSUE's hard acceptance line): a telemetry=1
engine produces bit-identical results — full state/fault pytrees, cut
sequences, configuration-id chains, decision rounds — to the telemetry=0
engine on every driver spelling (per-step, fused convergence, multi-cut
wave, fleet lockstep, streaming pipeline). The lanes themselves must be
path-independent: the fused ``run_to_decision_telem`` while-loop and a
per-step drive accumulate the same counters, and a fleet tenant's lanes
match a per-cluster drive exactly (the wave's coast-gating pin promised in
``fleet_wave_telem_impl``'s docstring).

Budget (the PR-10 convention): the small-grid cluster+fleet+stream
differentials are the compile-bearing tier-1 representatives; the larger
geometry grid rides the unfiltered check.sh pass behind ``slow``. The
quiescent-zero pin mirrors the ``quiescent_round_activity == 0`` fact
frozen in tools/analysis/hlo.lock.json.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.serving import PoissonChurn, StreamDriver
from rapid_tpu.tenancy import TenantFleet
from rapid_tpu.utils.engine_telemetry import TELEMETRY_DIGEST_FIELDS


def _cluster(telemetry, n=24, n_slots=40, seed=0, **kw):
    vc = VirtualCluster.create(
        n, n_slots=n_slots, k=3, h=3, l=1, cohorts=2, fd_threshold=2,
        seed=seed, telemetry=telemetry, **kw,
    )
    vc.assign_cohorts_roundrobin()
    return vc


def _trees_equal(a, b) -> bool:
    return bool(jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda x, y: bool((np.asarray(x) == np.asarray(y)).all()), a, b
    )))


def _lanes_host(telem):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), telem)


def _churn_drive(vc, steps=10):
    """Crash + join churn through the per-step seam; the test_tenancy cut
    labeling, so both sides of every differential observe identically."""
    cuts, ids, rounds = [], [], []
    joiners = np.nonzero(~np.asarray(vc.state.alive))[0][:2].tolist()
    vc.crash([3, 5])
    for i in range(steps):
        if i == 4:
            vc.inject_join_wave(joiners)
        was_alive = np.asarray(vc.state.alive)
        events = vc.step()
        if bool(events.decided):
            mask = np.asarray(events.winner_mask)
            cuts.append(frozenset(
                (s, "down" if was_alive[s] else "up")
                for s in np.nonzero(mask)[0].tolist()
            ))
            ids.append(vc.config_id)
            rounds.append(i)
    return cuts, ids, rounds


def test_step_drive_bit_identical_telemetry_on_off():
    """The tier-1 representative: one crash+join churn drive, telemetry on
    vs off — identical cuts, config-id chains, decision rounds, and final
    state AND fault pytrees, leaf for leaf."""
    off = _cluster(telemetry=False)
    on = _cluster(telemetry=True)
    expected = _churn_drive(off)
    got = _churn_drive(on)
    assert expected[0], "drive produced no cuts — the differential is vacuous"
    assert got == expected
    assert _trees_equal(on.state, off.state)
    assert _trees_equal(on.faults, off.faults)
    assert on.config_id == off.config_id
    assert on.config_epoch == off.config_epoch
    # And the lanes saw the drive: rounds counted, alerts/decisions nonzero.
    on.sync()
    activity = on.activity
    assert activity["rounds"] == 10
    assert activity["alerts"] > 0
    assert activity["decisions_fast"] + activity["decisions_classic"] == len(
        expected[0]
    )
    assert off.activity is None  # telemetry=0: no lanes, no fetch, ever


def test_fused_convergence_bit_identical_and_lanes_path_independent():
    """``run_to_decision``/``run_until_membership`` (the fused while-loop
    drivers) decide identically with telemetry on; the lanes a fused drive
    accumulates equal a per-step drive's lanes exactly (path independence —
    the while-loop body IS the step body)."""
    off = _cluster(telemetry=False, seed=1)
    on = _cluster(telemetry=True, seed=1)
    stepped = _cluster(telemetry=True, seed=1)
    off.crash([2, 7]); on.crash([2, 7]); stepped.crash([2, 7])

    expected = off.run_to_decision(max_steps=32)
    got = on.run_to_decision(max_steps=32)
    assert got[0] == expected[0] and got[1] == expected[1]  # rounds, decided
    assert got[3] == expected[3]  # membership after the cut
    np.testing.assert_array_equal(np.asarray(got[2]), np.asarray(expected[2]))
    assert _trees_equal(on.state, off.state)

    for _ in range(got[0]):
        stepped.step()
    assert _trees_equal(_lanes_host(on.telem), _lanes_host(stepped.telem))

    # The multi-cut wave: same resolution, same config chain, on vs off.
    off2 = _cluster(telemetry=False, seed=2)
    on2 = _cluster(telemetry=True, seed=2)
    for vc in (off2, on2):
        vc.crash([1, 4, 9])
    expected2 = off2.run_until_membership(21, max_steps=64, min_cuts=1)
    got2 = on2.run_until_membership(21, max_steps=64, min_cuts=1)
    assert got2 == expected2
    assert _trees_equal(on2.state, off2.state)
    assert on2.config_id == off2.config_id


def _fleet(telemetry, b=3, n=16, seed0=10):
    clusters = []
    for i in range(b):
        vc = VirtualCluster.create(
            n, k=3, h=3, l=1, cohorts=2, fd_threshold=2, seed=seed0 + i,
            telemetry=telemetry,
        )
        vc.assign_cohorts_roundrobin()
        # Tenant i loses i+1 members: every tenant resolves at a DIFFERENT
        # round, so the wave's coast-gating is genuinely exercised.
        vc.crash(list(range(1, 2 + i)))
        clusters.append(vc)
    return clusters


def test_fleet_wave_lanes_bit_identical_to_per_cluster_drives():
    """The fleet_wave_telem coast-gating pin: tenants resolving at different
    rounds coast frozen — no phantom lane accumulation — so each tenant's
    lanes equal its own per-cluster ``run_until_membership`` drive, raw
    int32 for raw int32; and the wave itself matches the telemetry=0 wave."""
    singles = _fleet(telemetry=True)
    targets = [vc.membership_size - (1 + i) for i, vc in enumerate(singles)]
    expected = [
        vc.run_until_membership(t, max_steps=64, min_cuts=1)
        for vc, t in zip(singles, targets)
    ]
    assert all(r[2] for r in expected), "a tenant failed to resolve"

    fleet = TenantFleet.from_clusters(_fleet(telemetry=True))
    rounds, cuts, resolved, _ = fleet.run_until_membership(
        np.asarray(targets), max_steps=64, min_cuts=1
    )
    assert resolved.all()
    assert rounds.tolist() == [r[0] for r in expected]
    assert cuts.tolist() == [r[1] for r in expected]
    for t, vc in enumerate(singles):
        tenant_lanes = jax.tree_util.tree_map(
            lambda x, t=t: np.asarray(x)[t], fleet.telem
        )
        assert _trees_equal(tenant_lanes, _lanes_host(vc.telem)), t
    assert _trees_equal(
        fleet.state,
        jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *(vc.state for vc in singles)
        ),
    )

    # Same wave, telemetry off: the fleet results are unchanged.
    off = TenantFleet.from_clusters(_fleet(telemetry=False))
    rounds0, cuts0, resolved0, _ = off.run_until_membership(
        np.asarray(targets), max_steps=64, min_cuts=1
    )
    assert resolved0.all()
    assert rounds0.tolist() == rounds.tolist()
    assert cuts0.tolist() == cuts.tolist()
    assert _trees_equal(off.state, fleet.state)

    # The digest boundary agrees with the raw-lane comparison.
    fleet.sync()
    for t, vc in enumerate(singles):
        vc.sync()
        single_activity = vc.activity
        for field in TELEMETRY_DIGEST_FIELDS:
            assert fleet.tenant_activity[t][field] == single_activity[field]


def test_stream_drive_bit_identical_and_drain_is_the_fetch_boundary():
    """The streaming pipeline with telemetry on: bit-identical cuts/state to
    the telemetry=0 stream, and the drain — the pipeline's fetch seam — is
    where the activity cache refreshes (zero-minted before, measured
    after)."""
    waves = PoissonChurn(24, 40, rate=1.0, seed=7).waves(6)

    on = _cluster(telemetry=True, seed=0)
    assert on.activity["rounds"] == 0  # zero-minted at attach
    driver_on = StreamDriver(on, rounds_per_wave=4, depth=2)
    for wave in waves:
        driver_on.submit(wave)
    result_on = driver_on.drain()

    off = _cluster(telemetry=False, seed=0)
    driver_off = StreamDriver(off, rounds_per_wave=4, depth=2)
    for wave in waves:
        driver_off.submit(wave)
    result_off = driver_off.drain()

    assert result_on.cuts == result_off.cuts
    assert result_on.waves == result_off.waves == 6
    assert _trees_equal(on.state, off.state)
    assert _trees_equal(on.faults, off.faults)
    assert on.config_id == off.config_id

    activity = on.activity
    assert activity["rounds"] == result_on.rounds == 24
    assert activity["decisions_fast"] + activity["decisions_classic"] == (
        result_on.cuts
    )
    assert 0.0 < activity["active_fraction"] <= 1.0


def test_sharded_telem_wave_bit_identical_and_fleet_lanes_shard():
    """The lanes under a real device mesh: the sharded telem wave
    (``make_sharded_wave_telem``) matches the single-device fused drive
    bit for bit — results AND lanes — and tenant-stacked lanes place onto
    the 3-D fleet mesh through the same rule table
    (``fleet_telemetry_shardings``: leading 'tenant' axis on every leaf,
    values unchanged by placement)."""
    from rapid_tpu.parallel.mesh import (
        TENANT_AXIS,
        fleet_telemetry_shardings,
        make_mesh,
        make_sharded_wave_telem,
        shard_faults,
        shard_pytree,
        shard_state,
        telemetry_shardings,
    )

    single = _cluster(telemetry=True, seed=6)
    single.crash([2, 7])
    r1, c1, resolved1, _ = single.run_until_membership(
        22, max_steps=64, min_cuts=1
    )
    assert resolved1

    vc = _cluster(telemetry=True, seed=6)
    vc.crash([2, 7])
    mesh = make_mesh(jax.devices()[:8])
    wave = make_sharded_wave_telem(vc.cfg, mesh, max_cuts=8)
    state, telem, steps, cuts, resolved, _ = wave(
        shard_state(vc.state, mesh),
        shard_pytree(vc.telem, telemetry_shardings(mesh), mesh=mesh),
        shard_faults(vc.faults, mesh),
        jnp.int32(22), jnp.int32(64), jnp.int32(1),
    )
    assert bool(resolved)
    assert (int(steps), int(cuts)) == (r1, c1)
    assert _trees_equal(state, single.state)
    assert _trees_equal(_lanes_host(telem), _lanes_host(single.telem))

    # Tenant-stacked lanes on the ('tenant', 'cohort', 'nodes') mesh.
    singles = _fleet(telemetry=True, b=4)
    targets = [vc.membership_size - (1 + i) for i, vc in enumerate(singles)]
    fleet = TenantFleet.from_clusters(singles)
    _, _, resolved_f, _ = fleet.run_until_membership(
        np.asarray(targets), max_steps=64, min_cuts=1
    )
    assert resolved_f.all()
    shardings = fleet_telemetry_shardings(mesh3d := make_mesh(
        jax.devices()[:8], shape=(2, 2, 2)
    ))
    for leaf in jax.tree_util.tree_leaves(shardings):
        assert leaf.spec and leaf.spec[0] == TENANT_AXIS
    placed = shard_pytree(fleet.telem, shardings, mesh=mesh3d)
    assert _trees_equal(_lanes_host(placed), _lanes_host(fleet.telem))


def test_quiescent_soak_reads_exactly_zero_activity():
    """The zero-churn fact frozen in the HLO lock
    (``quiescent_round_activity == 0``): an event-free soak counts its
    rounds and NOTHING else — any nonzero counter here is phantom
    activity."""
    vc = _cluster(telemetry=True, seed=5)
    for _ in range(16):
        vc.step()
    vc.sync()
    activity = vc.activity
    assert activity["rounds"] == 16
    for field in TELEMETRY_DIGEST_FIELDS:
        if field != "rounds":
            assert activity[field] == 0, field
    assert activity["rounds_undecided_hist"] == [0] * len(
        activity["rounds_undecided_hist"]
    )
    assert activity["active_fraction"] == 0.0
    assert activity["conflict_rate"] == 0.0


@pytest.mark.slow
def test_second_geometry_grid_bit_identical():
    """The wider on/off differential grid (second geometries: more slots,
    four cohorts, nonzero delivery spread, compact storage). Rides the
    unfiltered check.sh pass; tier-1 keeps the single-geometry
    representatives above as the acceptance pins."""
    for n, n_slots, cohorts, spread, compact, seed in [
        (48, 64, 4, 1, False, 3),
        (32, 48, 2, 0, True, 4),
    ]:
        def build(telemetry):
            vc = VirtualCluster.create(
                n, n_slots=n_slots, k=4, h=3, l=1, cohorts=cohorts,
                fd_threshold=2, delivery_spread=spread, compact=compact,
                seed=seed, telemetry=telemetry,
            )
            vc.assign_cohorts_roundrobin()
            return vc

        off, on = build(False), build(True)
        expected = _churn_drive(off, steps=14)
        got = _churn_drive(on, steps=14)
        assert expected[0], (n, "no cuts")
        assert got == expected, (n, n_slots, cohorts)
        assert _trees_equal(on.state, off.state), (n, n_slots, cohorts)
        assert on.config_id == off.config_id
