"""Self-healing serving runtime (rapid_tpu/serving/supervisor + recovery):
deadline-bounded dispatch, seeded retry/backoff, crash-consistent
checkpoint/resume, and per-tenant quarantine — every failure injected by a
seeded ``SupervisorFaultPlan`` and every recovery verified BIT-IDENTICAL.

The acceptance bars (ISSUE 15):

- an injected mid-stream failure and a simulated process kill between
  waves, followed by supervisor resume, yield cuts, config-id chains, and
  final state pytrees bit-identical to the uninterrupted run — for BOTH
  the ``VirtualCluster`` and ``TenantFleet`` serving shapes;
- quarantining one poisoned tenant leaves the other B-1 tenants'
  results bit-identical to a fleet built without it (vmap independence,
  now load-bearing for degradation);
- wedges are LOUD: a never-ready ticket (or a lost one) raises
  ``DispatchWedgedError`` naming the phase and wave index at the declared
  budget, on the INJECTED clock — no real waiting in these tests.

Budget (the PR-10 convention): every compile-bearing test reuses the
test_stream geometries (n=24/n_slots=40/k=3 cluster, b=3/n=16 fleet), so
the engine executables are shared across the session; deadline/backoff
mechanics run on fake clocks and never sleep; the wider drill grid rides
the unfiltered check.sh pass behind ``slow``.
"""

import json

import numpy as np
import pytest

import jax

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.serving import (
    BackoffPolicy,
    DispatchWedgedError,
    FleetPoissonChurn,
    PoissonChurn,
    SimulatedProcessKill,
    StreamWave,
    Supervisor,
    SupervisorBudgets,
    SupervisorFaultPlan,
    recovery,
)
from rapid_tpu.tenancy import TenantFleet
from rapid_tpu.utils import exposition
from rapid_tpu.utils.checkpoint import CheckpointCorruptError
from rapid_tpu.utils.ledger import RunLedger, read_ledger


def _cluster(seed=0):
    vc = VirtualCluster.create(
        24, n_slots=40, k=3, h=3, l=1, cohorts=2, fd_threshold=2, seed=seed
    )
    vc.assign_cohorts_roundrobin()
    return vc


def _fleet(seeds=(10, 11, 12)):
    clusters = []
    for s in seeds:
        vc = VirtualCluster.create(
            16, k=3, h=3, l=1, cohorts=2, fd_threshold=2, seed=s
        )
        vc.assign_cohorts_roundrobin()
        clusters.append(vc)
    return TenantFleet.from_clusters(clusters)


def _trees_equal(a, b) -> bool:
    return bool(jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda x, y: bool((np.asarray(x) == np.asarray(y)).all()), a, b
    )))


def _tenant_slices_equal(tree_a, ia, tree_b, ib) -> bool:
    return bool(jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda x, y: bool(
            (np.asarray(x)[ia] == np.asarray(y)[ib]).all()
        ), tree_a, tree_b,
    )))


class FakeClock:
    """Injected decision clock: advances only when the fake sleep runs, so
    deadline tests are exact and never wait."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, seconds):
        self.t += seconds


# ---------------------------------------------------------------------------
# Budgets & backoff: pure, declared, seeded
# ---------------------------------------------------------------------------


def test_backoff_schedule_is_a_pure_function_of_its_seed():
    a = BackoffPolicy(max_attempts=5, seed=3).delays_ms()
    b = BackoffPolicy(max_attempts=5, seed=3).delays_ms()
    assert a == b and len(a) == 4
    # Exponential envelope with bounded seeded jitter.
    for i, delay in enumerate(a):
        step = 2.0 * 2.0**i
        assert step <= delay <= step * 1.25
    # A different seed is a different jitter sequence.
    assert a != BackoffPolicy(max_attempts=5, seed=4).delays_ms()


def test_budget_table_covers_declared_phases_only():
    budgets = SupervisorBudgets(submit_ms=10.0)
    assert budgets.for_phase("submit") == 10.0
    assert budgets.for_phase("drain") == SupervisorBudgets().drain_ms
    with pytest.raises(ValueError, match="no deadline budget"):
        budgets.for_phase("made_up_phase")


# ---------------------------------------------------------------------------
# Deadline-bounded dispatch: wedges are loud, named, and clock-injected
# ---------------------------------------------------------------------------


def test_wedged_dispatch_raises_named_error_at_the_budget():
    clock = FakeClock()
    vc = _cluster()
    sup = Supervisor(
        vc, rounds_per_wave=2, depth=1,
        budgets=SupervisorBudgets(submit_ms=50.0, drain_ms=40.0),
        fault_plan=SupervisorFaultPlan(wedge_wave=0),
        clock=clock, sleep=clock.sleep,
    )
    sup.submit(StreamWave(crash=(3,)))
    with pytest.raises(DispatchWedgedError) as exc:
        sup.drain()
    # The error names the phase and wave index — "wave 0 wedged in drain",
    # never an anonymous 240 s idle.
    assert exc.value.phase == "drain" and exc.value.wave_index == 0
    assert "wave 0" in str(exc.value)
    # The deadline fired on the INJECTED clock at the declared budget.
    assert clock.t * 1000.0 >= 40.0
    assert vc.metrics.counters["engine_recovery_wedges"] == 1


def test_wedge_fires_at_depth_two_despite_the_opportunistic_reaper():
    """A plan-wedged ticket must survive the reaper at any pipeline depth:
    without the fault-aware readiness probe, depth>1 would retire the wave
    through the REAL is_ready probe before any bounded wait saw it, and
    the injected fault would silently never fire."""
    clock = FakeClock()
    vc = _cluster()
    sup = Supervisor(
        vc, rounds_per_wave=2, depth=2,
        budgets=SupervisorBudgets(drain_ms=30.0),
        fault_plan=SupervisorFaultPlan(wedge_wave=0),
        clock=clock, sleep=clock.sleep,
    )
    churn = PoissonChurn(24, 40, rate=1.0, seed=5)
    sup.submit(churn.wave())
    sup.submit(churn.wave())  # depth not yet exceeded: reaper runs, must skip wave 0
    vc.sync()  # wave 0's REAL ticket is now ready — the plan still holds it
    with pytest.raises(DispatchWedgedError) as exc:
        sup.drain()
    assert exc.value.phase == "drain" and exc.value.wave_index == 0


def test_backpressure_wait_wedges_under_the_submit_budget():
    clock = FakeClock()
    vc = _cluster()
    sup = Supervisor(
        vc, rounds_per_wave=2, depth=1,
        budgets=SupervisorBudgets(submit_ms=30.0),
        fault_plan=SupervisorFaultPlan(lose_ticket_wave=0),
        clock=clock, sleep=clock.sleep,
    )
    sup.submit(StreamWave(crash=(5,)))
    # depth=1: the next submit must first wait on wave 0's (lost) ticket.
    with pytest.raises(DispatchWedgedError) as exc:
        sup.submit(StreamWave(crash=(6,)))
    assert exc.value.phase == "submit" and exc.value.wave_index == 0
    assert "ticket lost" in str(exc.value)


def test_transient_failures_retry_on_the_seeded_schedule():
    import time as _time

    slept = []

    def sleep(seconds):
        slept.append(seconds)
        _time.sleep(seconds)  # the injected sleep also serves poll waits

    vc = _cluster()
    # Backoff delays (base 50 ms) are far above the poll interval (0.5 ms),
    # so the recorded sleeps separate cleanly into poll ticks vs retries.
    policy = BackoffPolicy(max_attempts=4, base_ms=50.0, seed=9)
    sup = Supervisor(
        vc, rounds_per_wave=2, poll_ms=0.5,
        backoff=policy,
        fault_plan=SupervisorFaultPlan(transient_submit=((0, 2),)),
        sleep=sleep,
    )
    sup.submit(StreamWave(crash=(3,)))  # two injected failures, then lands
    assert vc.metrics.counters["engine_recovery_retries"] == 2
    # The backoff sleeps are exactly the first two seeded schedule delays.
    expected = [d / 1000.0 for d in policy.delays_ms()[:2]]
    assert [s for s in slept if s >= 0.01] == expected
    sup.drain()
    assert sup.driver.waves_completed == 1


def test_exhausted_retries_escalate_to_dispatch_wedged():
    vc = _cluster()
    sup = Supervisor(
        vc, rounds_per_wave=2,
        backoff=BackoffPolicy(max_attempts=3, seed=1),
        fault_plan=SupervisorFaultPlan(transient_submit=((0, 99),)),
        sleep=lambda s: None,
    )
    with pytest.raises(DispatchWedgedError) as exc:
        sup.submit(StreamWave(crash=(3,)))
    assert exc.value.phase == "submit" and exc.value.wave_index == 0
    assert "retries exhausted" in str(exc.value)
    assert vc.metrics.counters["engine_recovery_retries"] == 3
    assert vc.metrics.counters["engine_recovery_wedges"] == 1


# ---------------------------------------------------------------------------
# Kill/resume differential: the acceptance bar, both serving shapes
# ---------------------------------------------------------------------------


def test_cluster_kill_resume_is_bit_identical(tmp_path):
    """A transient failure mid-schedule + a simulated process kill between
    waves; resume from the newest checkpoint replays the seeded churn to a
    final state, cut count, and config-id chain bit-identical to the
    uninterrupted run — with the whole recovery timeline in the ledger."""
    waves = PoissonChurn(24, 40, rate=1.0, seed=7).waves(6)

    unbroken = _cluster()
    sup_u = Supervisor(unbroken, rounds_per_wave=4, depth=2)
    for wave in waves:
        sup_u.submit(wave)
    result_u = sup_u.drain()
    assert result_u.cuts > 0, "schedule produced no cuts — vacuous differential"

    ledger = RunLedger(str(tmp_path / "ledger.jsonl"))
    drill = _cluster()
    sup_d = Supervisor(
        drill, rounds_per_wave=4, depth=2,
        checkpoint_dir=tmp_path / "ckpt", checkpoint_every=2,
        fault_plan=SupervisorFaultPlan(
            transient_submit=((1, 2),), kill_after_wave=3,
        ),
        ledger=ledger, ledger_stage="recovery", sleep=lambda s: None,
    )
    churn = iter(waves)
    with pytest.raises(SimulatedProcessKill) as kill:
        for wave in churn:
            sup_d.submit(wave)
    assert kill.value.wave_index == 3

    resumed_sup, next_wave = recovery.resume(
        tmp_path / "ckpt", checkpoint_every=2,
        ledger=ledger, ledger_stage="recovery",
    )
    assert next_wave == 4  # checkpoint cadence 2, killed after wave 3
    assert resumed_sup.last_resume_ms is not None
    for wave in waves[next_wave:]:
        resumed_sup.submit(wave)
    resumed_sup.drain()

    resumed = resumed_sup.target
    assert _trees_equal(resumed.state, unbroken.state)
    assert _trees_equal(resumed.faults, unbroken.faults)
    assert resumed.config_id == unbroken.config_id
    assert resumed.config_epoch == unbroken.config_epoch
    # The recovery timeline is a first-class ledger record.
    events, skipped = read_ledger(str(tmp_path / "ledger.jsonl"))
    kinds = [e["event"] for e in events]
    assert skipped == 0
    assert kinds.count("recovery_retry") == 2
    assert "recovery_checkpoint" in kinds and "recovery_resume" in kinds
    [resume_event] = [e for e in events if e["event"] == "recovery_resume"]
    assert resume_event["wave"] == 4 and resume_event["mttr_ms"] > 0


def test_corrupt_checkpoint_falls_back_to_previous_valid_one(tmp_path):
    """The fault plan corrupts the NEWEST checkpoint after its atomic
    publish; resume must skip it loudly (CheckpointCorruptError handled,
    ledger event emitted) and replay from the older valid one — still
    bit-identical."""
    waves = PoissonChurn(24, 40, rate=1.0, seed=7).waves(6)
    unbroken = _cluster()
    sup_u = Supervisor(unbroken, rounds_per_wave=4, depth=2)
    for wave in waves:
        sup_u.submit(wave)
    sup_u.drain()

    drill = _cluster()
    sup_d = Supervisor(
        drill, rounds_per_wave=4, depth=2,
        checkpoint_dir=tmp_path / "ckpt", checkpoint_every=2,
        fault_plan=SupervisorFaultPlan(
            kill_after_wave=3, corrupt_checkpoint_at=4,
        ),
        sleep=lambda s: None,
    )
    with pytest.raises(SimulatedProcessKill):
        for wave in waves:
            sup_d.submit(wave)
    # The damaged newest file fails its integrity check by name...
    newest, loaded, skipped = recovery.latest_valid_checkpoint(tmp_path / "ckpt")
    assert newest is not None and "w00000002" in newest.name
    assert loaded is not None and len(skipped) == 1
    with pytest.raises(CheckpointCorruptError):
        from rapid_tpu.utils.checkpoint import load_serving_state

        load_serving_state(tmp_path / "ckpt" / "ckpt_w00000004.npz")
    # ...and resume falls back to the wave-2 checkpoint and replays.
    ledger = RunLedger(str(tmp_path / "ledger.jsonl"))
    resumed_sup, next_wave = recovery.resume(
        tmp_path / "ckpt", ledger=ledger, ledger_stage="recovery",
    )
    assert next_wave == 2
    for wave in waves[next_wave:]:
        resumed_sup.submit(wave)
    resumed_sup.drain()
    assert _trees_equal(resumed_sup.target.state, unbroken.state)
    assert resumed_sup.target.config_id == unbroken.config_id
    events, _ = read_ledger(str(tmp_path / "ledger.jsonl"))
    assert any(e["event"] == "recovery_checkpoint_corrupt" for e in events)


def test_truncated_checkpoint_and_empty_dir_are_loud(tmp_path):
    drill = _cluster()
    sup = Supervisor(
        drill, rounds_per_wave=2, checkpoint_dir=tmp_path / "ckpt",
        checkpoint_every=1, checkpoint_keep=1,
        fault_plan=SupervisorFaultPlan(truncate_checkpoint_at=1),
    )
    sup.submit(StreamWave(crash=(3,)))
    # keep=1 and the only checkpoint truncated: nothing valid to resume.
    with pytest.raises(FileNotFoundError, match="no valid checkpoint"):
        recovery.resume(tmp_path / "ckpt")
    with pytest.raises(FileNotFoundError, match="no valid checkpoint"):
        recovery.resume(tmp_path / "never-written")


def test_checkpoints_prune_to_keep(tmp_path):
    vc = _cluster()
    sup = Supervisor(
        vc, rounds_per_wave=2, checkpoint_dir=tmp_path / "ckpt",
        checkpoint_every=1, checkpoint_keep=2,
    )
    for wave in PoissonChurn(24, 40, rate=0.5, seed=2).waves(5):
        sup.submit(wave)
    sup.drain()
    names = sorted(p.name for p in (tmp_path / "ckpt").iterdir())
    assert names == ["ckpt_w00000004.npz", "ckpt_w00000005.npz"]
    assert sup.checkpoints_written == 5


def test_fleet_kill_resume_is_bit_identical(tmp_path):
    """The TenantFleet serving shape: per-tenant Poisson crash streams,
    killed between waves, resumed from the stacked checkpoint (knob lanes
    included) — per-tenant config ids, epochs, and the full stacked pytree
    bit-identical to the uninterrupted fleet."""
    waves = FleetPoissonChurn(3, 16, rate=0.7, seed=3).waves(5)

    unbroken = _fleet()
    sup_u = Supervisor(unbroken, rounds_per_wave=3, depth=2)
    for wave in waves:
        sup_u.submit(wave)
    sup_u.drain()

    drill = _fleet()
    sup_d = Supervisor(
        drill, rounds_per_wave=3, depth=2,
        checkpoint_dir=tmp_path / "ckpt", checkpoint_every=2,
        fault_plan=SupervisorFaultPlan(kill_after_wave=2),
    )
    with pytest.raises(SimulatedProcessKill):
        for wave in waves:
            sup_d.submit(wave)

    resumed_sup, next_wave = recovery.resume(tmp_path / "ckpt")
    assert next_wave == 2
    resumed = resumed_sup.target
    assert isinstance(resumed, TenantFleet) and resumed.b == 3
    churn = recovery.fast_forward(
        FleetPoissonChurn(3, 16, rate=0.7, seed=3), next_wave
    )
    for _ in range(next_wave, 5):
        resumed_sup.submit(churn.wave())
    resumed_sup.drain()
    assert _trees_equal(resumed.state, unbroken.state)
    assert _trees_equal(resumed.faults, unbroken.faults)
    assert _trees_equal(resumed.knobs, unbroken.knobs)
    assert resumed.config_ids() == unbroken.config_ids()
    np.testing.assert_array_equal(
        resumed.config_epochs(), unbroken.config_epochs()
    )


@pytest.mark.slow
def test_kill_resume_grid(tmp_path):
    """Wider drill grid (kill points x cadences x seeds). Rides the
    unfiltered check.sh pass; tier-1 keeps the single-point cluster and
    fleet differentials above as the acceptance pins."""
    for seed, kill_after, every in [(1, 1, 1), (2, 4, 3), (3, 2, 2)]:
        waves = PoissonChurn(24, 40, rate=1.5, seed=seed).waves(6)
        unbroken = _cluster()
        sup_u = Supervisor(unbroken, rounds_per_wave=3, depth=2)
        for wave in waves:
            sup_u.submit(wave)
        sup_u.drain()
        ckpt = tmp_path / f"ckpt{seed}"
        drill = _cluster()
        sup_d = Supervisor(
            drill, rounds_per_wave=3, depth=2, checkpoint_dir=ckpt,
            checkpoint_every=every,
            fault_plan=SupervisorFaultPlan(kill_after_wave=kill_after),
        )
        with pytest.raises(SimulatedProcessKill):
            for wave in waves:
                sup_d.submit(wave)
        resumed_sup, next_wave = recovery.resume(ckpt)
        for wave in waves[next_wave:]:
            resumed_sup.submit(wave)
        resumed_sup.drain()
        label = f"seed={seed} kill={kill_after} every={every}"
        assert _trees_equal(resumed_sup.target.state, unbroken.state), label
        assert resumed_sup.target.config_id == unbroken.config_id, label


# ---------------------------------------------------------------------------
# Quarantine: detect, freeze, export, keep the other B-1 serving
# ---------------------------------------------------------------------------


def _poison_tenant(fleet, t):
    """Corrupt one tenant's membership bookkeeping (the class of damage a
    bad host write or a partial upload leaves): n_members diverges from the
    alive population and leaves the legal range."""
    fleet.state = fleet.state._replace(
        n_members=fleet.state.n_members.at[t].set(-3)
    )


def test_health_scan_is_clean_on_a_healthy_fleet():
    fleet = _fleet()
    fleet.faults = fleet.faults._replace(
        crashed=fleet.faults.crashed.at[:, 3].set(True)
    )
    fleet.run_until_membership(15, max_steps=64, min_cuts=1)
    assert not fleet.health_scan().any()
    assert fleet.tenant_health_report(0) == []


def test_quarantine_freezes_poisoned_tenant_and_spares_the_rest(tmp_path):
    """The degradation bar: the poisoned tenant is detected by the device
    health reduction, frozen in place through the SAME compiled wave
    program (the per-tenant done lane — data, no recompile), exported as a
    replayable repro, and the other B-1 tenants' results are bit-identical
    to a fleet that never contained it."""
    fleet_a = _fleet((10, 11, 12))
    _poison_tenant(fleet_a, 1)
    scan = fleet_a.health_scan()
    np.testing.assert_array_equal(scan, [False, True, False])

    sup = Supervisor(fleet_a, rounds_per_wave=2)
    fresh = sup.scan_and_quarantine(repro_dir=tmp_path)
    assert fresh == [1] and fleet_a.quarantined == (1,)
    assert sup.scan_and_quarantine() == []  # idempotent
    report = fleet_a.tenant_health_report(1)
    assert any("n_members=-3" in line for line in report)

    # The B-1 control: same seeds, the poisoned tenant never existed.
    fleet_b = _fleet((10, 12))
    for fleet in (fleet_a, fleet_b):
        fleet.faults = fleet.faults._replace(
            crashed=fleet.faults.crashed.at[:, 3].set(True)
        )
    rounds_a, cuts_a, resolved_a, _ = fleet_a.run_until_membership(
        15, max_steps=64, min_cuts=1
    )
    rounds_b, cuts_b, resolved_b, _ = fleet_b.run_until_membership(
        15, max_steps=64, min_cuts=1
    )
    for ia, ib in ((0, 0), (2, 1)):
        assert _tenant_slices_equal(fleet_a.state, ia, fleet_b.state, ib)
        assert rounds_a[ia] == rounds_b[ib] and cuts_a[ia] == cuts_b[ib]
    # The quarantined tenant sat bit-frozen: zero rounds, zero cuts.
    assert rounds_a[1] == 0 and cuts_a[1] == 0
    ids = fleet_a.config_ids()
    ids_b = fleet_b.config_ids()
    assert [ids[0], ids[2]] == ids_b
    # Telemetry: census gauge + counter, JSON-serializable snapshot.
    snap = fleet_a.telemetry_snapshot()
    assert snap["engine"]["tenancy"]["quarantined"] == 1
    assert fleet_a.metrics.counters["engine_tenant_quarantines"] == 1
    json.dumps(snap)

    # The exported repro replays deterministically: same violations.
    repro = tmp_path / "tenant1"
    assert (repro / "fleet.json").exists()
    recipe = json.loads((repro / "fleet.json").read_text())
    assert recipe["kind"] == "quarantine" and recipe["tenant_index"] == 1
    replayed = recovery.replay_quarantine_repro(repro)
    recorded = [
        line for line in (repro / "violations.txt").read_text().splitlines()
        if line and line != "(none)"
    ]
    assert replayed == recorded and replayed


def test_chaosrun_replay_recognizes_quarantine_repro(tmp_path, capsys):
    fleet = _fleet((20, 21, 22))
    _poison_tenant(fleet, 2)
    sup = Supervisor(fleet, rounds_per_wave=2)
    sup.scan_and_quarantine(repro_dir=tmp_path)

    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
    import chaosrun

    # Violations reproduce -> exit 1 (a repro that stops failing is news).
    rc = chaosrun.main(["replay", str(tmp_path / "tenant2")])
    out = capsys.readouterr()
    assert rc == 1
    assert "VIOLATION" in out.out and "DIVERGED" not in out.err


def test_supervised_waves_drop_churn_for_quarantined_tenants():
    fleet = _fleet()
    _poison_tenant(fleet, 0)
    sup = Supervisor(fleet, rounds_per_wave=2)
    sup.scan_and_quarantine()
    from rapid_tpu.serving import FleetWave

    sup.submit(FleetWave(crash=((0, 5), (1, 5))))
    sup.drain()
    # Tenant 0's pair was dropped (its freeze is the wave-path done lane;
    # feeding churn to a frozen tenant would sit unresolved forever);
    # tenant 1's landed.
    assert not bool(np.asarray(fleet.faults.crashed)[0, 5])
    assert bool(np.asarray(fleet.faults.crashed)[1, 5])
    assert fleet.metrics.counters[
        "engine_recovery_quarantine_dropped_events"
    ] == 1


# ---------------------------------------------------------------------------
# Observability: the recovery section's golden names
# ---------------------------------------------------------------------------

GOLDEN_RECOVERY_METRIC_NAMES = sorted(
    [
        f"rapid_engine_recovery_{key}"
        for key in (
            "waves_submitted", "checkpoint_every", "checkpoints_written",
            "last_checkpoint_wave", "retries", "wedges", "resumes",
            "quarantined", "mttr_ms",
        )
    ]
    + [
        f"rapid_engine_recovery_{key}_total"
        for key in (
            "retries", "wedges", "checkpoints", "resumes", "quarantines",
            "quarantine_dropped_events",
        )
    ]
)


def test_recovery_prometheus_names_are_golden_and_attach_gated():
    vc = _cluster()
    vc.step()
    before = exposition.metric_names(vc.prometheus_text())
    assert not any("recovery" in name for name in before)
    Supervisor(vc, rounds_per_wave=2)  # attach, zero traffic
    after = exposition.metric_names(vc.prometheus_text())
    recovery_names = sorted(n for n in after if "recovery" in n)
    assert recovery_names == GOLDEN_RECOVERY_METRIC_NAMES
    # Supervision implies the stream tier (the Supervisor owns a
    # StreamDriver); beyond those two additions the vocabulary is
    # unchanged — supervision never renames or drops a batch series.
    residue = sorted(
        n for n in after
        if "recovery" not in n and "stream" not in n
    )
    assert residue == sorted(before)
    json.dumps(vc.telemetry_snapshot())


def test_clustertop_renders_recovery_pane(tmp_path):
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
    import clustertop

    vc = _cluster()
    sup = Supervisor(
        vc, rounds_per_wave=2, checkpoint_dir=tmp_path, checkpoint_every=1
    )
    sup.submit(StreamWave(crash=(3,)))
    sup.drain()
    frame = clustertop.render_frame([vc.telemetry_snapshot()])
    assert "RECOVERY" in frame and "CKPTS" in frame
    # Pre-supervision snapshots render no recovery pane, never a crash.
    plain = _cluster()
    plain.step()
    assert "CKPTS" not in clustertop.render_frame([plain.telemetry_snapshot()])
