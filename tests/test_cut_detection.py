"""MultiNodeCutDetector tests, mirroring the reference's CutDetectionTest
scenarios (rapid/src/test/java/com/vrg/rapid/CutDetectionTest.java)."""

import pytest

from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.types import AlertMessage, EdgeStatus, Endpoint, NodeId

K, H, L = 10, 8, 2
CONFIG_ID = -1


def alert(src: Endpoint, dst: Endpoint, status: EdgeStatus, ring_number: int) -> AlertMessage:
    return AlertMessage(
        edge_src=src,
        edge_dst=dst,
        edge_status=status,
        configuration_id=CONFIG_ID,
        ring_numbers=(ring_number,),
    )


def src(i: int) -> Endpoint:
    return Endpoint("127.0.0.1", i)


def test_invalid_watermarks_rejected():
    for k, h, l in [(2, 2, 1), (10, 11, 2), (10, 8, 9), (10, 8, 0)]:
        with pytest.raises(ValueError):
            MultiNodeCutDetector(k, h, l)


def test_cut_detection_basic():
    wb = MultiNodeCutDetector(K, H, L)
    dst = Endpoint("127.0.0.2", 2)
    for i in range(H - 1):
        assert wb.aggregate(alert(src(i + 1), dst, EdgeStatus.UP, i)) == []
        assert wb.num_proposals == 0
    ret = wb.aggregate(alert(src(H), dst, EdgeStatus.UP, H - 1))
    assert ret == [dst]
    assert wb.num_proposals == 1


def test_cut_detection_one_blocker():
    wb = MultiNodeCutDetector(K, H, L)
    dst1 = Endpoint("127.0.0.2", 2)
    dst2 = Endpoint("127.0.0.3", 2)
    for i in range(H - 1):
        assert wb.aggregate(alert(src(i + 1), dst1, EdgeStatus.UP, i)) == []
    for i in range(H - 1):
        assert wb.aggregate(alert(src(i + 1), dst2, EdgeStatus.UP, i)) == []
    assert wb.aggregate(alert(src(H), dst1, EdgeStatus.UP, H - 1)) == []
    assert wb.num_proposals == 0
    ret = wb.aggregate(alert(src(H), dst2, EdgeStatus.UP, H - 1))
    assert len(ret) == 2
    assert set(ret) == {dst1, dst2}
    assert wb.num_proposals == 1


def test_cut_detection_three_blockers():
    wb = MultiNodeCutDetector(K, H, L)
    dsts = [Endpoint(f"127.0.0.{i}", 2) for i in (2, 3, 4)]
    for dst in dsts:
        for i in range(H - 1):
            assert wb.aggregate(alert(src(i + 1), dst, EdgeStatus.UP, i)) == []
    assert wb.aggregate(alert(src(H), dsts[0], EdgeStatus.UP, H - 1)) == []
    assert wb.aggregate(alert(src(H), dsts[2], EdgeStatus.UP, H - 1)) == []
    assert wb.num_proposals == 0
    ret = wb.aggregate(alert(src(H), dsts[1], EdgeStatus.UP, H - 1))
    assert set(ret) == set(dsts)
    assert wb.num_proposals == 1


def test_cut_detection_blockers_past_h():
    wb = MultiNodeCutDetector(K, H, L)
    dsts = [Endpoint(f"127.0.0.{i}", 2) for i in (2, 3, 4)]
    for dst in dsts:
        for i in range(H - 1):
            assert wb.aggregate(alert(src(i + 1), dst, EdgeStatus.UP, i)) == []
    # Duplicate ring announcements past H are ignored.
    wb.aggregate(alert(src(H), dsts[0], EdgeStatus.UP, H - 1))
    assert wb.aggregate(alert(src(H + 1), dsts[0], EdgeStatus.UP, H - 1)) == []
    wb.aggregate(alert(src(H), dsts[2], EdgeStatus.UP, H - 1))
    assert wb.aggregate(alert(src(H + 1), dsts[2], EdgeStatus.UP, H - 1)) == []
    assert wb.num_proposals == 0
    ret = wb.aggregate(alert(src(H), dsts[1], EdgeStatus.UP, H - 1))
    assert set(ret) == set(dsts)
    assert wb.num_proposals == 1


def test_cut_detection_below_l_does_not_block():
    wb = MultiNodeCutDetector(K, H, L)
    dst1 = Endpoint("127.0.0.2", 2)
    dst2 = Endpoint("127.0.0.3", 2)
    dst3 = Endpoint("127.0.0.4", 2)
    for i in range(H - 1):
        assert wb.aggregate(alert(src(i + 1), dst1, EdgeStatus.UP, i)) == []
    for i in range(L - 1):
        assert wb.aggregate(alert(src(i + 1), dst2, EdgeStatus.UP, i)) == []
    for i in range(H - 1):
        assert wb.aggregate(alert(src(i + 1), dst3, EdgeStatus.UP, i)) == []
    assert wb.aggregate(alert(src(H), dst1, EdgeStatus.UP, H - 1)) == []
    assert wb.num_proposals == 0
    ret = wb.aggregate(alert(src(H), dst3, EdgeStatus.UP, H - 1))
    assert set(ret) == {dst1, dst3}
    assert wb.num_proposals == 1


def test_cut_detection_batch():
    wb = MultiNodeCutDetector(K, H, L)
    endpoints = [Endpoint("127.0.0.2", 2 + i) for i in range(3)]
    proposal = []
    for endpoint in endpoints:
        for ring_number in range(K):
            proposal.extend(wb.aggregate(alert(src(1), endpoint, EdgeStatus.UP, ring_number)))
    assert len(proposal) == len(endpoints)


def test_link_invalidation():
    view = MembershipView(K)
    wb = MultiNodeCutDetector(K, H, L)
    num_nodes = 30
    endpoints = []
    for i in range(num_nodes):
        node = Endpoint("127.0.0.2", 2 + i)
        endpoints.append(node)
        view.ring_add(node, NodeId(0, i))

    dst = endpoints[0]
    observers = view.observers_of(dst)
    assert len(observers) == K

    # Alerts from observers[0, H-1) about dst: dst stuck at H-1 reports.
    for i in range(H - 1):
        assert wb.aggregate(alert(observers[i], dst, EdgeStatus.DOWN, i)) == []

    # Alerts about observers[H-1, K) of dst: those observers cross H.
    failed_observers = set()
    for i in range(H - 1, K):
        observers_of_observer = view.observers_of(observers[i])
        failed_observers.add(observers[i])
        for j in range(K):
            assert (
                wb.aggregate(alert(observers_of_observer[j], observers[i], EdgeStatus.DOWN, j))
                == []
            )
    assert wb.num_proposals == 0

    # Implicit edge invalidation brings dst and the failed observers into one cut.
    ret = wb.invalidate_failing_edges(view)
    assert len(ret) == 4
    assert wb.num_proposals == 1
    for node in ret:
        assert node in failed_observers or node == dst


def test_invalidation_without_down_events_is_noop():
    view = MembershipView(K)
    wb = MultiNodeCutDetector(K, H, L)
    for i in range(10):
        view.ring_add(Endpoint("127.0.0.2", 2 + i), NodeId(0, i))
    assert wb.invalidate_failing_edges(view) == []


def test_clear_resets_all_state():
    wb = MultiNodeCutDetector(K, H, L)
    dst = Endpoint("127.0.0.2", 2)
    for i in range(H):
        wb.aggregate(alert(src(i + 1), dst, EdgeStatus.UP, i))
    assert wb.num_proposals == 1
    wb.clear()
    assert wb.num_proposals == 0
    # Same alerts go through again from scratch.
    for i in range(H - 1):
        assert wb.aggregate(alert(src(i + 1), dst, EdgeStatus.UP, i)) == []
    assert wb.aggregate(alert(src(H), dst, EdgeStatus.UP, H - 1)) == [dst]
