"""Property-based safety invariants of the virtual-cluster engine.

Randomized fault patterns (crash sets, join waves, cohort splits, delivery
jitter) against the invariants that hold for EVERY execution:

- a decided cut flips exactly its winner set, and the winner contains only
  faulted members and pending joiners — a healthy, un-faulted member is
  never evicted;
- membership arithmetic stays consistent (n_members == popcount(alive));
- a fast-round decision is quorum-backed; a decision below the fast quorum
  can only come from the classic fallback, which needs fallback_rounds of
  stall first.

One static engine config (shapes fixed) so hypothesis examples reuse the
compiled executable; only data varies.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # property tests need it; the rest of the suite doesn't
from hypothesis import given, settings, strategies as st

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.protocol.fast_paxos import fast_paxos_quorum

N = 64
SLOTS = 72


def run_scenario(seed, victims, joiners, n_cohorts_used, spread_used):
    vc = VirtualCluster.create(
        N, n_slots=SLOTS, k=10, h=8, l=3, cohorts=8, fd_threshold=2,
        seed=seed, delivery_spread=2,
    )
    rng = np.random.default_rng(seed)
    vc.assign_cohorts(rng.integers(0, n_cohorts_used, size=SLOTS).astype(np.int32))
    if spread_used:
        vc.stagger_fd_counts(rng, spread_rounds=2)
    if joiners:
        vc.inject_join_wave(joiners)
    if victims:
        vc.crash(victims)
    return vc


@settings(max_examples=60, deadline=None)
@given(
    seed=st.integers(0, 2**31 - 1),
    n_victims=st.integers(0, 6),
    n_joiners=st.integers(0, 8),
    n_cohorts_used=st.integers(1, 8),
    spread_used=st.booleans(),
)
def test_decided_cuts_touch_only_faulted_and_joining(
    seed, n_victims, n_joiners, n_cohorts_used, spread_used
):
    rng = np.random.default_rng(seed ^ 0xA5A5)
    victims = sorted(rng.choice(N, size=n_victims, replace=False).tolist())
    joiners = list(range(N, N + n_joiners))
    vc = run_scenario(seed, victims, joiners, n_cohorts_used, spread_used)

    flippable = set(victims) | set(joiners)
    members = N
    rounds_in_config = 0
    for _ in range(64):
        events = vc.step()
        rounds_in_config += 1
        if bool(events.decided):
            winner = set(np.nonzero(np.asarray(events.winner_mask))[0].tolist())
            assert winner, "decided with an empty cut"
            assert winner <= flippable, (
                f"cut {winner} touches healthy members (allowed: {flippable})"
            )
            if int(events.max_votes) < fast_paxos_quorum(members):
                # Below the fast quorum only the classic fallback may decide,
                # and it cannot fire before the stall window elapses (a
                # first-step announce can stall-decide exactly AT the
                # window, hence >=).
                assert rounds_in_config >= vc.cfg.fallback_rounds
            members = vc.membership_size
            rounds_in_config = 0
        # Membership arithmetic is always consistent.
        alive = np.asarray(vc.state.alive)
        assert int(vc.state.n_members) == int(alive.sum())
        if not (set(np.nonzero(~alive[:N])[0].tolist()) ^ set(victims)) and not (
            set(np.nonzero(alive[N : N + n_joiners])[0].tolist())
            ^ set(range(n_joiners))
        ):
            break  # scenario fully resolved

    # Whatever was decided, no healthy original member was ever evicted.
    alive = np.asarray(vc.state.alive)
    healthy = np.ones(N, dtype=bool)
    healthy[victims] = False
    assert alive[:N][healthy].all(), "healthy member evicted"


@settings(max_examples=40, deadline=None)
@given(
    seed=st.integers(0, 2**31 - 1),
    n_victims=st.integers(1, 6),
    n_joiners=st.integers(0, 8),
    spread_used=st.booleans(),
)
def test_fused_wave_matches_sequential_decisions(
    seed, n_victims, n_joiners, spread_used
):
    # For EVERY fault/join pattern, the whole-wave single-dispatch loop
    # (run_until_membership) must commit exactly what the per-decision
    # driver commits: same rounds, same cut count, same final view. Shapes
    # fixed so all examples share the two compiled executables.
    rng = np.random.default_rng(seed ^ 0x5A5A)
    victims = sorted(rng.choice(N, size=n_victims, replace=False).tolist())
    joiners = list(range(N, N + n_joiners))
    target = N - n_victims + n_joiners

    def build():
        return run_scenario(seed, victims, joiners, 8, spread_used)

    seq = build()
    seq_rounds, seq_cuts = 0, 0
    while seq.membership_size != target or seq_cuts == 0:
        rounds, decided, _, _ = seq.run_to_decision(max_steps=64)
        assert decided, "sequential driver did not converge"
        seq_rounds += rounds
        seq_cuts += 1
        assert seq_cuts <= 8

    fused = build()
    # Same total budget as the sequential reference (8 cuts x 64 rounds):
    # the fused loop's max_steps is cumulative across cuts.
    fused_budget = 8 * 64
    rounds, cuts, resolved, sizes = fused.run_until_membership(
        target, max_steps=fused_budget, min_cuts=1
    )
    assert resolved
    assert (rounds, cuts) == (seq_rounds, seq_cuts)
    assert sizes[-1] == target
    np.testing.assert_array_equal(fused.alive_mask, seq.alive_mask)
    assert fused.config_id == seq.config_id
