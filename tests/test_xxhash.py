"""XXH64 correctness against published test vectors."""

from rapid_tpu.utils.xxhash import xxh64, xxh64_int


def test_empty_seed0():
    assert xxh64(b"", 0) == 0xEF46DB3751D8E999


def test_long_input():
    # Spans the >=32-byte main loop (39 bytes); vector from python-xxhash docs.
    assert xxh64(b"Nobody inspects the spammish repetition", 0) == 0xFBCEA83C8A378BF1


def test_seed_changes_hash():
    h = {xxh64(b"rapid-tpu", seed) for seed in range(16)}
    assert len(h) == 16


def test_lengths_cover_all_tails():
    # 0..40 bytes exercises the 8/4/1-byte tail paths and the main loop.
    seen = set()
    for n in range(41):
        seen.add(xxh64(bytes(range(n)), 7))
    assert len(seen) == 41


def test_int_hash_signed_unsigned_agree():
    # The same 64-bit pattern hashes identically regardless of sign convention.
    assert xxh64_int(-1) == xxh64_int((1 << 64) - 1)
    assert xxh64_int(0) != xxh64_int(1)
