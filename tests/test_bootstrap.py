"""Bootstrap-wave scenario (paper Fig. 5 / Table 1 analog).

The paper's Table 1 cleanliness claim: a thundering herd of joiners is
admitted through a handful of large batched cuts (4-10 unique intermediate
cluster sizes at N=2000), not ~N one-at-a-time view changes. The engine
replays this in examples/bootstrap_bench.py; these tests pin the invariants
at test scale.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from examples.bootstrap_bench import run_bootstrap


def test_bootstrap_wave_admits_everyone_in_one_cut_per_wave():
    r = run_bootstrap(
        n_total=256, seed_size=16, waves=4, cohorts=8, delivery_spread=0
    )
    assert r["unique_sizes"][0] == 16
    assert r["unique_sizes"][-1] == 256
    # Without delivery jitter each wave lands as exactly one consensus cut.
    assert r["view_changes"] == 4
    assert len(r["unique_sizes"]) == 5  # Table 1: O(waves), not O(N)
    sizes = r["unique_sizes"]
    assert all(a < b for a, b in zip(sizes, sizes[1:])), "growth is monotone"


@pytest.mark.slow
def test_bootstrap_under_delivery_jitter_still_admits_everyone():
    # Rides the unfiltered check.sh pass (a second full bootstrap compile
    # with jitter enabled); the clean-wave bootstrap test above keeps the
    # Table-1 cleanliness pin in tier-1.
    r = run_bootstrap(
        n_total=192, seed_size=12, waves=3, cohorts=16, delivery_spread=2,
        seed=7,
    )
    assert r["unique_sizes"][-1] == 192
    # Jitter may split a wave into a couple of cuts, never into ~N.
    assert r["view_changes"] <= 2 * r["waves"]


def test_bootstrap_single_giant_wave():
    """The whole herd in ONE batching window — the hardest cleanliness case:
    a 15x-membership join wave lands in a bounded number of cuts."""
    r = run_bootstrap(
        n_total=512, seed_size=32, waves=1, cohorts=8, delivery_spread=1,
        seed=3,
    )
    assert r["unique_sizes"][-1] == 512
    assert r["view_changes"] <= 4


def test_bootstrap_refuses_double_admission():
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    vc = VirtualCluster.create(8, n_slots=16, cohorts=2, seed=0)
    vc.inject_join_wave([8, 9])
    with pytest.raises(ValueError, match="not admissible"):
        vc.inject_join_wave([9])  # already pending
    with pytest.raises(ValueError, match="not admissible"):
        vc.inject_join_wave([0])  # already a member


def test_lifecycle_mutations_reject_out_of_range_slots():
    """jnp scatter CLAMPS out-of-range indices; the engine must raise
    instead of silently mutating slot n-1 (or no-opping a join)."""
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    vc = VirtualCluster.create(8, n_slots=16, cohorts=2, seed=0)
    for mutate, arg in [
        (vc.inject_join_wave, [16]),
        (vc.crash, [-17]),
        (vc.revive, [16]),
        (vc.initiate_leave, [99]),
    ]:
        with pytest.raises(IndexError, match="out of range"):
            mutate(arg)
