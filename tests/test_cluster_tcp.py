"""Full cluster over the real TCP transport (the reference's
concurrentNodeJoinsNetty analog, ClusterTest.java:249-268)."""

import asyncio
import functools
import random

from rapid_tpu.messaging.tcp import TcpClient, TcpServer
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint

from helpers import wait_until

BASE_PORT = 23100


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=60)

        asyncio.run(with_timeout())

    return wrapper


def fast_settings() -> Settings:
    s = Settings()
    s.batching_window_ms = 20
    s.failure_detector_interval_ms = 50
    s.rpc_timeout_ms = 500
    s.rpc_join_timeout_ms = 2000
    s.rpc_probe_timeout_ms = 200
    s.consensus_fallback_base_delay_ms = 2000
    return s


def ep(i: int) -> Endpoint:
    return Endpoint("127.0.0.1", BASE_PORT + i)



def tcp_transport(addr: Endpoint, settings: Settings):
    return TcpClient(addr, settings), TcpServer(addr)


@async_test
async def test_five_nodes_over_tcp_with_failure():
    settings = fast_settings()
    fd = StaticFailureDetectorFactory()
    client, server = tcp_transport(ep(0), settings)
    clusters = [
        await Cluster.start(ep(0), settings=settings, client=client, server=server,
                            fd_factory=fd, rng=random.Random(0))
    ]
    for i in range(1, 5):
        client, server = tcp_transport(ep(i), settings)
        clusters.append(
            await Cluster.join(ep(0), ep(i), settings=settings, client=client, server=server,
                               fd_factory=fd, rng=random.Random(i))
        )
    try:
        assert await wait_until(
            lambda: all(c.membership_size == 5 for c in clusters)
            and len({tuple(c.membership) for c in clusters}) == 1
        )
        # Crash one node for real: kill its server, blacklist it in the FD.
        victim = clusters[3]
        await victim.shutdown()
        fd.add_failed_nodes([victim.listen_address])
        survivors = [c for c in clusters if c is not victim]
        assert await wait_until(
            lambda: all(c.membership_size == 4 for c in survivors)
        )
        assert all(victim.listen_address not in c.membership for c in survivors)
    finally:
        await asyncio.gather(*(c.shutdown() for c in clusters), return_exceptions=True)
