"""Tier-1 chaos smoke: the ten scenario families over pinned seeds, every
oracle, explicit CPU budget.

50 pinned (family, seed) runs — the four flat families (partition-heal,
asymmetric link, crash-during-join, churn-under-loss), the two adversarial
families (false_alert_stability, watermark_probe — Byzantine observers
against the H/L watermarks), and the four WAN-shaped hierarchical families
(wan_cohort_asym, delegate_gray_failure, cohort_boundary_flap,
committee_crash_during_reconfig — profile="hier", two cohorts,
rapid_tpu/hier) at 5 seeds each — each through the FULL oracle battery
including the host<->device differential replay. One test drives the whole grid so the
asserted budget covers everything: the budget is process CPU time (wall
clock would flake under CI contention), and it bounds what the tier-1 gate
is allowed to spend on chaos coverage — a regression that slows simulated
runs 5x is a finding, not an inconvenience. Schedule-space *search*
(fuzzing many random seeds) is the slow-marked job in test_sim_fuzz.py;
this is coverage, pinned."""

import time

import pytest

from rapid_tpu.sim.fuzz import FAMILIES, run_schedule, scenario_family
from rapid_tpu.sim.oracles import check_all

#: 5 pinned seeds per family = 50 pinned scenarios in tier-1.
SEEDS = (1, 2, 3, 4, 5)

#: Process-CPU budget for the full grid, including the engine compile the
#: first differential replay pays (~7 s) and JAX/CPU variance headroom: the
#: grid measures ~45 s on an idle container.
CPU_BUDGET_S = 280.0


def test_pinned_chaos_grid_upholds_every_oracle():
    started = time.process_time()
    failures = []
    runs = 0
    for family in sorted(FAMILIES):
        for seed in SEEDS:
            schedule = scenario_family(family, seed)
            result = run_schedule(schedule)
            violations = check_all(result)  # differential included
            runs += 1
            if violations:
                failures.append(
                    f"{schedule.name}: "
                    + "; ".join(str(v) for v in violations)
                )
            if not result.cuts and schedule.membership_phases():
                # Zero cuts is vacuous ONLY when the schedule demands
                # membership changes; the stable-band adversarial family
                # (false_alert_stability) holds every report below H, so
                # "no cut ever" IS the asserted outcome there.
                failures.append(f"{schedule.name}: produced no cuts (vacuous run)")
    spent = time.process_time() - started
    assert runs == len(FAMILIES) * len(SEEDS) == 50
    assert not failures, "\n".join(failures)
    assert spent < CPU_BUDGET_S, (
        f"chaos smoke burned {spent:.1f}s CPU (budget {CPU_BUDGET_S}s): "
        "simulated runs regressed"
    )


def test_family_runs_are_deterministic():
    # The subsystem's foundational claim: a run is a pure function of its
    # schedule. Same family, same seed, fresh event loop -> identical cut
    # sequence, configuration chains, and outcome.
    a = run_schedule(scenario_family("churn_under_loss", 9))
    b = run_schedule(scenario_family("churn_under_loss", 9))
    assert a.cuts == b.cuts
    assert a.configs == b.configs
    assert a.final_membership == b.final_membership
    assert a.final_converge_sim_ms == b.final_converge_sim_ms
    assert a.shaper_stats == b.shaper_stats
    # And the loss schedule genuinely shaped traffic (not a vacuous pass).
    assert a.shaper_stats["dropped"] > 0


def test_hier_family_runs_are_deterministic():
    # The hierarchical profile upholds the same purity claim: same family,
    # same seed, fresh event loop -> identical chains and outcome — the
    # cohort map, delegate forwarding, and global tier introduce no hidden
    # entropy. And the WAN asymmetry genuinely shaped cross-cohort traffic.
    a = run_schedule(scenario_family("wan_cohort_asym", 7))
    b = run_schedule(scenario_family("wan_cohort_asym", 7))
    assert a.cuts == b.cuts
    assert a.configs == b.configs
    assert a.final_membership == b.final_membership
    assert a.shaper_stats == b.shaper_stats
    assert a.shaper_stats["asym_dropped"] > 0


def test_repro_artifacts_feed_traceview(tmp_path):
    # The artifact directory a run writes is exactly what tools/traceview.py
    # renders end-to-end: per-node recordings plus the fault-injection lane.
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
    import traceview

    result = run_schedule(scenario_family("partition_heal", 2))
    result.write_repro(tmp_path)
    paths, faultlog = traceview.expand_scenario_dir(str(tmp_path))
    assert len(paths) == len(result.snapshots)
    assert faultlog is not None
    snapshots = traceview.load_snapshots(paths)
    lane = traceview.fault_snapshot(faultlog)
    events = traceview.merge_events(snapshots + [lane])
    names = {e["name"] for e in events}
    assert "fault:ingress_block" in names and "fault:crash" in names
    assert "fault:heal_partitions" in names
    assert "view_change" in names  # real recorder events merged alongside
    # The chaos lane renders in the Chrome trace like any node lane.
    chrome = traceview.chrome_trace(events)
    process_names = {
        e["args"]["name"] for e in chrome["traceEvents"]
        if e.get("name") == "process_name"
    }
    assert traceview.FAULT_LANE in process_names


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
