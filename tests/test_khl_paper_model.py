"""Fig. 11 reproduction is DERIVED, not tuned — and pinned here.

The paper's K/H/L sensitivity study (§Evaluation): deliver the F*K alerts
to each receiver in an independent uniform random order; a receiver
conflicts iff its first announced proposal misses a victim. The engine
realizes that model by derivation: iid uniform per-(cohort, edge) delivery
delays of large spread induce exactly such a permutation per cohort
(examples/khl_sensitivity.py module docstring).

This test cross-checks the ENGINE's detector experiment against a direct
numpy implementation of the paper's model (same announce rule, true
permutations, no time quantization) at two pinned cells, and pins the
paper's qualitative laws. Tolerances are wide enough for sampling noise at
CI-sized rep counts but far tighter than the effects being pinned (the
worst cell conflicts ~20x more often than gap-5).
"""

import numpy as np
import pytest

K = 10
N = 1000
COHORTS = 64


def direct_paper_model(h, l, f, receivers, seed):
    """The paper's simulation, literally: per receiver an independent
    uniform permutation of the F*K alerts, processed one at a time against
    the H/L announce rule (MultiNodeCutDetector semantics)."""
    rng = np.random.default_rng(seed)
    conflicted = 0
    alerts = np.repeat(np.arange(f), K)
    for _ in range(receivers):
        order = rng.permutation(alerts)
        tally = np.zeros(f, dtype=int)
        for v in order:
            tally[v] += 1
            if (tally >= h).any() and not ((tally >= l) & (tally < h)).any():
                if (tally >= h).sum() < f:
                    conflicted += 1
                break
    return conflicted / receivers


import functools


@functools.cache
def _khl_module():
    # Load once: re-executing the module would reset its _EXPERIMENT jit
    # cache and force redundant XLA recompiles per engine_rate call.
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "khl_sensitivity",
        Path(__file__).parent.parent / "examples" / "khl_sensitivity.py",
    )
    khl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(khl)
    return khl


def engine_rate(h, l, f, reps, seed0):
    khl = _khl_module()
    conflicted = base = 0
    for rep in range(reps):
        c, _, _ = khl.run_once(N, K, h, l, f, COHORTS, seed=seed0 + rep)
        conflicted += c
        base += COHORTS
    return conflicted / base


def test_engine_matches_direct_paper_model_worst_cell():
    # H=6, L=4, F=2 — the paper's worst cell (~30%+ conflict rate, Fig. 11).
    # 10 reps x 64 cohorts = 640 sampled receivers.
    engine = engine_rate(6, 4, 2, reps=10, seed0=100)
    direct = direct_paper_model(6, 4, 2, receivers=4000, seed=1)
    assert direct > 0.25, direct  # the paper's qualitative claim
    # Engine realizes the same model: agree within sampling noise.
    assert 0.5 * direct < engine < 1.5 * direct, (engine, direct)


@pytest.mark.slow
def test_gap_law_and_shipped_config():
    # The paper's law: conflicts fall steeply as H-L widens; the shipped
    # {10,9,3} configuration is near-conflict-free while the worst cell is
    # catastrophic.
    # Rides the unfiltered check.sh pass: three 10-rep sweeps are tier-1's
    # single largest call (~38 s wall on the 2-CPU container); the
    # worst-cell test above stays tier-1 as the paper-model representative.
    gap5 = engine_rate(9, 4, 2, reps=10, seed0=200)
    gap6 = engine_rate(9, 3, 2, reps=10, seed0=300)
    worst = engine_rate(6, 4, 2, reps=10, seed0=400)
    assert gap5 < 0.08  # paper: ~2%
    assert gap6 <= gap5  # widening the gap cannot hurt
    assert worst > 10 * max(gap5, 1e-9)  # the cliff between corner and mid-ladder
