"""tools/perfview.py: stage-timeline rendering of run ledgers, the perf
trajectory over the committed BENCH_r* rounds (with snapshot/stale/wedged
trust flags — the acceptance surface for "no blind perf points"), and the
Chrome trace output.
"""

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import perfview  # noqa: E402  — tools/perfview.py

from rapid_tpu.utils.ledger import LedgerEvent, RunLedger  # noqa: E402


def _complete_ledger(tmp_path, fail_in=None):
    path = tmp_path / "run.jsonl"
    ledger = RunLedger(str(path), run_id="r1")
    ledger.emit(LedgerEvent.RUN_BEGIN, mode="inline", git_rev="abc1234",
                code_hash="deadbeefdeadbeef")
    ledger.emit(LedgerEvent.ATTEMPT_BEGIN, attempt=1, attempts=2)
    for stage in ("devices_init", "state_build", "warmup_compile"):
        if stage == fail_in:
            try:
                with ledger.stage(stage, timeout_s=60):
                    raise RuntimeError("synthetic failure")
            except RuntimeError:
                pass
            ledger.emit(LedgerEvent.RUN_FAIL, error="RuntimeError",
                        last_completed_stage="state_build")
            ledger.close()
            return path
        with ledger.stage(stage, timeout_s=60, n=256):
            pass
    ledger.emit(LedgerEvent.COMPILE_STATS, stage="warmup_compile",
                compiles=4, compile_ms=4117.2)
    ledger.emit(LedgerEvent.RUN_END, outcome="completed")
    ledger.close()
    return path


def test_renders_complete_ledger_timeline(tmp_path, capsys):
    path = _complete_ledger(tmp_path)
    assert perfview.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "git_rev=abc1234" in out
    for stage in ("devices_init", "state_build", "warmup_compile"):
        assert stage in out
    assert "compile_stats" in out
    # Attempts are visible: a retried run must not read as one seamless run.
    assert "attempt_begin" in out and "attempt=1" in out
    assert "outcome: completed" in out


def test_renders_failed_ledger_pointing_at_last_stage(tmp_path, capsys):
    path = _complete_ledger(tmp_path, fail_in="warmup_compile")
    assert perfview.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "FAIL" in out
    assert "last completed stage: state_build" in out


def test_wedged_ledger_shows_open_stage(tmp_path, capsys):
    path = tmp_path / "run.jsonl"
    ledger = RunLedger(str(path))
    with ledger.stage("devices_init"):
        pass
    ledger.emit(LedgerEvent.STAGE_BEGIN, stage="state_build", timeout_s=900)
    ledger.close()  # process dies here; no end event ever lands
    assert perfview.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "OPEN" in out
    assert "still running or killed mid-run (in 'state_build')" in out


def test_trajectory_marks_r04_r05_snapshot_stale(capsys):
    """The acceptance criterion: the committed BENCH_r01-r05 trajectory
    renders without error and r04-r05 read as snapshot/stale replays."""
    rounds = sorted(str(p) for p in REPO.glob("BENCH_r0*.json"))
    assert len(rounds) >= 5
    assert perfview.main(rounds) == 0
    out = capsys.readouterr().out
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_")}
    for round_name in ("BENCH_r04", "BENCH_r05"):
        assert "snapshot" in lines[round_name]
        assert "stale" in lines[round_name]
    assert "wedged" in lines["BENCH_r03"]
    # The alert_deliveries_per_sec ≈ 4.96e10 class of derived-metric bug is
    # visible at a glance on every historical point that carries it.
    assert "suspect-rate" in lines["BENCH_r05"]


def test_trajectory_accepts_bare_metric_json(tmp_path, capsys):
    point = tmp_path / "round.json"
    point.write_text(json.dumps({
        "metric": "churn_resolution_ms_n256_churn5pct", "value": 15.0,
        "unit": "ms", "vs_baseline": 33.3, "platform": "cpu",
        "alert_deliveries_per_sec": 511515.0,
    }))
    hole = tmp_path / "hole.json"
    hole.write_text(json.dumps({
        "metric": "churn_resolution_ms_n100000",
        "error": "accelerator_unavailable",
    }))
    assert perfview.main([str(point), str(hole)]) == 0
    out = capsys.readouterr().out
    row = next(line for line in out.splitlines() if line.startswith("round"))
    assert "live" in row and "suspect-rate" not in row
    assert "hole" in next(line for line in out.splitlines()
                          if line.startswith("hole"))


def test_trajectory_renders_headline_column_and_flags_missing(tmp_path, capsys):
    """ISSUE 9: the n1M_crash1pct_ms headline renders as its own trajectory
    column; an AUDITED round (carries hlo_audit) that omits both the value
    and its explicit n1M_status marker flags headline-missing; pre-audit
    historical rounds are exempt."""
    audit = {"sharded2d_wave": {"collectives": 5, "hot_loop_collectives": 1,
                                "temp_bytes": 10, "donation_dropped": 0}}
    points = {
        # Pre-audit historical round: exempt (sorts first).
        "BENCH_r20.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        # Audited + measured headline: value in the N1M column, no flag.
        "BENCH_r21.json": {"metric": "m", "value": 100.0, "platform": "tpu",
                           "hlo_audit": audit, "n1M_status": "live",
                           "n1M_crash1pct_ms": 709.2},
        # Audited + explicit ramped marker (CPU stage-path run): no flag.
        "BENCH_r22.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, "n1M_status": "ramped:4096",
                           "xl_point_ms": 40.0, "xl_n": 4096},
        # Audited round that silently dropped the headline: flagged.
        "BENCH_r23.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    assert "N1M" in out.splitlines()[1]  # the trajectory header row
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r2")}
    assert "709.2ms" in lines["BENCH_r21"]
    assert "headline-missing" not in lines["BENCH_r21"]
    assert "ramped:4096" in lines["BENCH_r22"]
    assert "headline-missing" not in lines["BENCH_r22"]
    assert "headline-missing" in lines["BENCH_r23"]
    assert "headline-missing" not in lines["BENCH_r20"]  # pre-audit history


def test_trajectory_renders_fleet_column_and_flags_missing(tmp_path, capsys):
    """ISSUE 10: tenant_view_changes_per_sec renders as its own trajectory
    column with the existing trust flags; an AUDITED round that omits both
    the value and its explicit tenant_fleet_status marker flags
    fleet-missing; pre-audit historical rounds are exempt."""
    audit = {"fleet3d_wave": {"collectives": 74, "hot_loop_collectives": 74,
                              "temp_bytes": 10, "donation_dropped": 0}}
    points = {
        # Pre-audit historical round: exempt (sorts first).
        "BENCH_r30.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        # Audited + measured fleet point: value in the FLEET column.
        "BENCH_r31.json": {"metric": "m", "value": 100.0, "platform": "tpu",
                           "hlo_audit": audit, "n1M_status": "live",
                           "tenant_fleet_status": "live",
                           "tenant_view_changes_per_sec": 5120.0,
                           "fleet_tenants": 256},
        # Audited + explicit ramped marker (CPU stage-path run): no flag.
        "BENCH_r32.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, "n1M_status": "ramped:256",
                           "tenant_fleet_status": "ramped:8x64"},
        # Audited round that silently dropped the fleet point: flagged.
        "BENCH_r33.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, "n1M_status": "ramped:256"},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    assert "FLEET" in out.splitlines()[1]  # the trajectory header row
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r3")}
    assert "5120.0/s" in lines["BENCH_r31"]
    assert "fleet-missing" not in lines["BENCH_r31"]
    assert "ramped:8x64" in lines["BENCH_r32"]
    assert "fleet-missing" not in lines["BENCH_r32"]
    assert "fleet-missing" in lines["BENCH_r33"]
    assert "fleet-missing" not in lines["BENCH_r30"]  # pre-audit history


def test_trajectory_renders_stream_column_and_flags_missing(tmp_path, capsys):
    """ISSUE 11: stream_view_changes_per_sec renders as its own trajectory
    column (with the p99 alert->commit beside it) under the existing trust
    flags; an AUDITED round that omits both the value and its explicit
    stream_status marker flags stream-missing; pre-audit historical rounds
    are exempt."""
    audit = {"sharded2d_wave": {"collectives": 5, "hot_loop_collectives": 1,
                                "temp_bytes": 10, "donation_dropped": 0}}
    points = {
        # Pre-audit historical round: exempt (sorts first).
        "BENCH_r40.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        # Audited + measured stream point: rate + p99 in the STREAM column.
        "BENCH_r41.json": {"metric": "m", "value": 100.0, "platform": "tpu",
                           "hlo_audit": audit, "n1M_status": "live",
                           "tenant_fleet_status": "live",
                           "stream_status": "live",
                           "stream_view_changes_per_sec": 84.5,
                           "stream_p99_alert_to_commit_ms": 41.03,
                           "stream_overlap_efficiency": 0.91},
        # Audited + explicit ramped marker (CPU pipeline exercise): no flag.
        "BENCH_r42.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, "n1M_status": "ramped:256",
                           "tenant_fleet_status": "ramped:8x64",
                           "stream_status": "ramped:12x96"},
        # Audited round that silently dropped the stream point: flagged.
        "BENCH_r43.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, "n1M_status": "ramped:256",
                           "tenant_fleet_status": "ramped:8x64"},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    assert "STREAM" in out.splitlines()[1]  # the trajectory header row
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r4")}
    assert "84.5/s" in lines["BENCH_r41"]
    assert "p99=41.0ms" in lines["BENCH_r41"]
    assert "stream-missing" not in lines["BENCH_r41"]
    assert "ramped:12x96" in lines["BENCH_r42"]
    assert "stream-missing" not in lines["BENCH_r42"]
    assert "stream-missing" in lines["BENCH_r43"]
    assert "stream-missing" not in lines["BENCH_r40"]  # pre-audit history


def test_trajectory_renders_chaos_column_and_flags_missing(tmp_path, capsys):
    """ISSUE 12: chaos_scenarios_per_sec renders as its own trajectory
    column (with the fleet tenant count beside it) under the existing
    trust flags; an AUDITED round that omits both the value and its
    explicit chaos_status marker flags chaos-missing; pre-audit historical
    rounds are exempt."""
    audit = {"fleet3d_wave": {"collectives": 74, "hot_loop_collectives": 74,
                              "temp_bytes": 10, "donation_dropped": 0}}
    points = {
        # Pre-audit historical round: exempt (sorts first).
        "BENCH_r50.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        # Audited + measured chaos point: rate + tenants in CHAOS column.
        "BENCH_r51.json": {"metric": "m", "value": 100.0, "platform": "tpu",
                           "hlo_audit": audit, "n1M_status": "live",
                           "tenant_fleet_status": "live",
                           "stream_status": "live",
                           "chaos_status": "live",
                           "chaos_scenarios_per_sec": 412.5,
                           "chaos_tenants": 256},
        # Audited + explicit ramped marker (CPU stage-path run): no flag.
        "BENCH_r52.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, "n1M_status": "ramped:256",
                           "tenant_fleet_status": "ramped:8x64",
                           "stream_status": "ramped:12x96",
                           "chaos_status": "ramped:12x12"},
        # Audited round that silently dropped the chaos point: flagged.
        "BENCH_r53.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, "n1M_status": "ramped:256",
                           "tenant_fleet_status": "ramped:8x64",
                           "stream_status": "ramped:12x96"},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    assert "CHAOS" in out.splitlines()[1]  # the trajectory header row
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r5")}
    assert "412.5/s B=256" in lines["BENCH_r51"]
    assert "chaos-missing" not in lines["BENCH_r51"]
    assert "ramped:12x12" in lines["BENCH_r52"]
    assert "chaos-missing" not in lines["BENCH_r52"]
    assert "chaos-missing" in lines["BENCH_r53"]
    assert "chaos-missing" not in lines["BENCH_r50"]  # pre-audit history


def test_trajectory_renders_recovery_column_and_flags_missing(tmp_path, capsys):
    """ISSUE 15: recovery_mttr_ms renders as the RECOVERY trajectory
    column (with a DIVERGED callout when the resumed run failed its
    bit-identity check) under the existing trust flags; an AUDITED round
    that omits both the value and its explicit recovery_status marker
    flags recovery-missing; pre-audit historical rounds are exempt."""
    audit = {"step": {"collectives": 0, "hot_loop_collectives": 0,
                      "temp_bytes": 10, "donation_dropped": 0}}
    base = {"n1M_status": "ramped:256", "tenant_fleet_status": "ramped:8x64",
            "stream_status": "ramped:12x96", "chaos_status": "ramped:12x12",
            "mem_status": "computed:cpu"}
    points = {
        # Pre-audit historical round: exempt (sorts first).
        "BENCH_r60.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        # Audited + measured drill: the MTTR in the RECOVERY column.
        "BENCH_r61.json": {"metric": "m", "value": 100.0, "platform": "tpu",
                           "hlo_audit": audit, **base,
                           "recovery_status": "live",
                           "recovery_mttr_ms": 182.4,
                           "recovery_bit_identical": True},
        # A resume that DIVERGED is called out beside its MTTR.
        "BENCH_r62.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "recovery_status": "ramped:6x64",
                           "recovery_mttr_ms": 20.9,
                           "recovery_bit_identical": False},
        # Audited + explicit status marker only (skipped drill): no flag.
        "BENCH_r63.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "recovery_status": "skipped-budget"},
        # Audited round that silently dropped the drill: flagged.
        "BENCH_r64.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    assert "RECOVERY" in out.splitlines()[1]  # the trajectory header row
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r6")}
    assert "182.4ms mttr" in lines["BENCH_r61"]
    assert "DIVERGED" not in lines["BENCH_r61"]
    assert "recovery-missing" not in lines["BENCH_r61"]
    assert "20.9ms mttr DIVERGED" in lines["BENCH_r62"]
    assert "skipped-budget" in lines["BENCH_r63"]
    assert "recovery-missing" not in lines["BENCH_r63"]
    assert "recovery-missing" in lines["BENCH_r64"]
    assert "recovery-missing" not in lines["BENCH_r60"]  # pre-audit history


def test_trajectory_renders_mem_column_and_flags_missing(tmp_path, capsys):
    """ISSUE 13: bytes_per_member renders as the MEM trajectory column
    (compact figure with the wide one beside it) under the existing trust
    flags; an AUDITED round omitting both the value and its explicit
    mem_status marker flags mem-missing; pre-audit historical rounds are
    exempt."""
    audit = {"step": {"collectives": 0, "hot_loop_collectives": 0,
                      "temp_bytes": 10, "donation_dropped": 0}}
    common = {"n1M_status": "ramped:256", "tenant_fleet_status": "ramped:4x48",
              "stream_status": "ramped:6x48", "chaos_status": "ramped:4x12"}
    points = {
        # Pre-audit historical round: exempt (sorts first).
        "BENCH_r60.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        # Audited + measured memory point: bytes/member in the MEM column.
        "BENCH_r61.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **common,
                           "mem_status": "live:hlo-audit",
                           "bytes_per_member": 246.4,
                           "bytes_per_member_wide": 445.0},
        # Audited + explicit computed marker: status cell, no flag.
        "BENCH_r62.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **common,
                           "mem_status": "computed:audit-lacks-step-memory"},
        # Audited round that silently dropped the memory point: flagged.
        "BENCH_r63.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **common},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    assert "MEM" in out.splitlines()[1]  # the trajectory header row
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r6")}
    assert "246B/m (wide 445)" in lines["BENCH_r61"]
    assert "mem-missing" not in lines["BENCH_r61"]
    assert "computed:audit-lacks-step-memory" in lines["BENCH_r62"]
    assert "mem-missing" not in lines["BENCH_r62"]
    assert "mem-missing" in lines["BENCH_r63"]
    assert "mem-missing" not in lines["BENCH_r60"]  # pre-audit history


def test_trajectory_renders_activity_column_and_flags_missing(tmp_path, capsys):
    """ISSUE 16: the device-telemetry activity fraction renders as the
    ACTIVITY trajectory column (fast-path share beside it) under the
    existing trust flags; an AUDITED round that omits both the numeric
    ``stream_active_fraction`` and its explicit ``activity_status`` marker
    flags activity-missing; pre-audit historical rounds are exempt."""
    audit = {"step_telem": {"collectives": 0, "hot_loop_collectives": 0,
                            "temp_bytes": 10, "donation_dropped": 0}}
    base = {"n1M_status": "ramped:256", "tenant_fleet_status": "ramped:8x64",
            "stream_status": "ramped:12x96", "chaos_status": "ramped:12x12",
            "mem_status": "computed:cpu", "recovery_status": "skipped-budget"}
    points = {
        # Pre-audit historical round: exempt (sorts first).
        "BENCH_r70.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        # Audited + measured activity: fraction + fast share in the column.
        "BENCH_r71.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "activity_status": "measured",
                           "stream_active_fraction": 0.0417,
                           "stream_fast_path_share": 0.88},
        # Audited + explicit status marker only (stream stage skipped, so
        # the lanes never ran): status cell, no flag.
        "BENCH_r72.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "activity_status": "skipped-budget"},
        # Audited round that silently dropped the activity point: flagged.
        "BENCH_r73.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    assert "ACTIVITY" in out.splitlines()[1]  # the trajectory header row
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r7")}
    assert "4.2% fast=88%" in lines["BENCH_r71"]
    assert "activity-missing" not in lines["BENCH_r71"]
    assert "skipped-budget" in lines["BENCH_r72"]
    assert "activity-missing" not in lines["BENCH_r72"]
    assert "activity-missing" in lines["BENCH_r73"]
    assert "activity-missing" not in lines["BENCH_r70"]  # pre-audit history


def test_trajectory_renders_trace_column_and_flags_missing(tmp_path, capsys):
    """ISSUE 17: the round-trace ring's stream decomposition renders as the
    TRACE trajectory column (rounds-to-decision p99, worst wave beside it)
    under the same trust discipline as ACTIVITY: an AUDITED round that
    omits both the numeric ``round_trajectory.rounds_to_decision_p99`` and
    its explicit ``trace_status`` marker flags trace-missing; pre-audit
    historical rounds are exempt."""
    audit = {"step_trace": {"collectives": 0, "hot_loop_collectives": 0,
                            "temp_bytes": 10, "donation_dropped": 0}}
    base = {"n1M_status": "ramped:256", "tenant_fleet_status": "ramped:8x64",
            "stream_status": "ramped:12x96", "chaos_status": "ramped:12x12",
            "mem_status": "computed:cpu", "recovery_status": "skipped-budget",
            "activity_status": "skipped-budget"}
    points = {
        # Pre-audit historical round: exempt (sorts first).
        "BENCH_r80.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        # Audited + a measured trajectory: p99 + worst wave in the column.
        "BENCH_r81.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "trace_status": "measured",
                           "round_trajectory": {
                               "rounds_to_decision_p99": 3.0,
                               "rounds_to_decision_max": 4,
                           }},
        # Audited + explicit status marker only (trace=0 bench): status
        # cell, no flag.
        "BENCH_r82.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "trace_status": "skipped-budget"},
        # Audited round that silently dropped the trajectory: flagged.
        "BENCH_r83.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    assert "TRACE" in out.splitlines()[1]  # the trajectory header row
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r8")}
    assert "p99=3.0r max=4" in lines["BENCH_r81"]
    assert "trace-missing" not in lines["BENCH_r81"]
    assert "skipped-budget" in lines["BENCH_r82"]
    assert "trace-missing" not in lines["BENCH_r82"]
    assert "trace-missing" in lines["BENCH_r83"]
    assert "trace-missing" not in lines["BENCH_r80"]  # pre-audit history


def test_trajectory_renders_costfit_column_and_flags_missing(
    tmp_path, capsys
):
    """ISSUE 18: the scaling-law cost model renders as the COSTFIT
    trajectory column (the WORST fitted class across the round's audited
    entrypoints, quiescent collective payload beside it) under the same
    trust discipline as the other axes: an AUDITED round that omits both
    the ``cost_fit`` table and its explicit status marker flags
    cost-missing; pre-audit historical rounds are exempt."""
    audit = {"step": {"collectives": 0, "hot_loop_collectives": 0,
                      "temp_bytes": 10, "donation_dropped": 0}}
    base = {"n1M_status": "ramped:256", "tenant_fleet_status": "ramped:8x64",
            "stream_status": "ramped:12x96", "chaos_status": "ramped:12x12",
            "mem_status": "computed:cpu", "recovery_status": "skipped-budget",
            "activity_status": "skipped-budget",
            "trace_status": "skipped-budget"}
    points = {
        # Pre-audit historical round: exempt (sorts first).
        "BENCH_r90.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        # Audited + fitted table: the worst class (here the step's O(N*K)
        # dominates the sync's O(N)) + quiescent payload in the column.
        "BENCH_r91.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "cost_fit": {
                               "step": {"argument_bytes": "O(N*K)",
                                        "temp_bytes": "O(N)"},
                               "sync": {"argument_bytes": "O(N)"},
                           },
                           "quiescent_round_cost": {
                               "entrypoint": "sharded_step",
                               "collective_payload_bytes": 53218,
                               "hot_loop_payload_bytes": 0,
                           }},
        # Audited + explicit suppressed marker (smoke run): status cell,
        # no flag.
        "BENCH_r92.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "cost_fit": {"status":
                                        "suppressed:RAPID_TPU_BENCH_"
                                        "COST_LADDER=0"}},
        # Audited round that silently dropped the cost axis: flagged.
        "BENCH_r93.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    assert "COSTFIT" in out.splitlines()[1]  # the trajectory header row
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r9")}
    assert "worst=O(N*K) q=53218B" in lines["BENCH_r91"]
    assert "cost-missing" not in lines["BENCH_r91"]
    assert "suppressed:RAPID_TPU_BENCH_COST_LADDER=0" in lines["BENCH_r92"]
    assert "cost-missing" not in lines["BENCH_r92"]
    assert "cost-missing" in lines["BENCH_r93"]
    assert "cost-missing" not in lines["BENCH_r90"]  # pre-audit history


def test_trajectory_renders_oppty_column_and_flags_missing(
    tmp_path, capsys
):
    """ISSUE 19: the jaxpr dataflow provenance axis renders as the OPPTY
    trajectory column (opportunity-map coverage of the quiescent payload
    bytes + the proof verdicts) under the same trust discipline as the
    other axes: an AUDITED round that omits the ``dataflow`` block flags
    dataflow-missing; pre-provenance historical rounds are exempt."""
    audit = {"step": {"collectives": 0, "hot_loop_collectives": 0,
                      "temp_bytes": 10, "donation_dropped": 0}}
    base = {"n1M_status": "ramped:256", "tenant_fleet_status": "ramped:8x64",
            "stream_status": "ramped:12x96", "chaos_status": "ramped:12x12",
            "mem_status": "computed:cpu", "recovery_status": "skipped-budget",
            "activity_status": "skipped-budget",
            "trace_status": "skipped-budget",
            "cost_fit": {"status": "suppressed:RAPID_TPU_BENCH_"
                                   "COST_LADDER=0"}}
    points = {
        # Pre-provenance historical round: exempt (sorts first).
        "BENCH_r95.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        # Audited + proofs + coverage: both render in the column.
        "BENCH_r96.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "dataflow": {
                               "status": "ok",
                               "observer_silent": True,
                               "tenant_isolated": True,
                               "opportunity_coverage_pct": 99.69,
                           }},
        # Audited + explicit suppressed marker (smoke run): status cell,
        # no flag.
        "BENCH_r97.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "dataflow": {"status":
                                        "suppressed:RAPID_TPU_BENCH_"
                                        "DATAFLOW=0"}},
        # Audited round that silently dropped the provenance axis: flagged.
        "BENCH_r98.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base},
        # A failed proof must be visible at a glance, never "ok".
        "BENCH_r99.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit, **base,
                           "dataflow": {
                               "status": "findings:1",
                               "observer_silent": False,
                               "tenant_isolated": True,
                               "opportunity_coverage_pct": 95.0,
                           }},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    assert "OPPTY" in out.splitlines()[1]  # the trajectory header row
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r9")}
    assert "100%/ok" in lines["BENCH_r96"]
    assert "dataflow-missing" not in lines["BENCH_r96"]
    assert "suppressed:RAPID_TPU_BENCH_DATAFLOW=0" in lines["BENCH_r97"]
    assert "dataflow-missing" not in lines["BENCH_r97"]
    assert "dataflow-missing" in lines["BENCH_r98"]
    assert "dataflow-missing" not in lines["BENCH_r95"]  # pre-provenance
    assert "95%/LEAK" in lines["BENCH_r99"]


def test_chrome_trace_envelope(tmp_path, capsys):
    path = _complete_ledger(tmp_path)
    chrome_path = tmp_path / "trace.json"
    assert perfview.main([str(path), "--chrome", str(chrome_path)]) == 0
    with open(chrome_path) as f:
        chrome = json.load(f)
    # Same envelope traceview emits (Perfetto/chrome://tracing load it).
    assert set(chrome) == {"traceEvents", "displayTimeUnit"}
    assert chrome["displayTimeUnit"] == "ms"
    stages = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in stages} == {
        "devices_init", "state_build", "warmup_compile",
    }
    for event in stages:
        assert event["dur"] >= 0 and isinstance(event["ts"], (int, float))
    instants = [e for e in chrome["traceEvents"] if e["ph"] == "i"]
    assert any(e["name"] == "compile_stats" for e in instants)


def test_multi_run_ledger_renders_one_section_per_run(tmp_path, capsys):
    # The default bench_ledger.jsonl accumulates runs across invocations;
    # each run must render as its own timeline with its own outcome, never
    # one merged timeline under the first run's provenance.
    path = tmp_path / "run.jsonl"
    first = RunLedger(str(path), run_id="run-one")
    first.emit(LedgerEvent.RUN_BEGIN, mode="inline", git_rev="aaa1111")
    with first.stage("devices_init"):
        pass
    first.emit(LedgerEvent.RUN_END, outcome="completed")
    first.close()
    second = RunLedger(str(path), run_id="run-two")
    second.emit(LedgerEvent.RUN_BEGIN, mode="watchdogged", git_rev="bbb2222")
    second.emit(LedgerEvent.RUN_FAIL, outcome="wedged",
                last_completed_stage=None)
    second.close()
    assert perfview.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "[run-one]" in out and "[run-two]" in out
    one, two = out.split("[run-two]")
    assert "outcome: completed" in one and "FAILED" not in one
    assert "outcome: FAILED (wedged)" in two
    runs = perfview.split_runs(perfview.read_ledger(str(path))[0])
    assert [run_id for run_id, _ in runs] == ["run-one", "run-two"]


def test_outcome_is_latest_terminal_event_not_first_fail(tmp_path, capsys):
    # A --cpu-fallback/--allow-snapshot run records the wedge (run_fail)
    # and THEN closes successfully (run_end): the latest terminal event
    # decides the outcome, with the earlier wedge still on display.
    path = tmp_path / "run.jsonl"
    ledger = RunLedger(str(path), run_id="r")
    ledger.emit(LedgerEvent.RUN_BEGIN, mode="watchdogged")
    ledger.emit(LedgerEvent.RUN_FAIL, outcome="wedged",
                last_completed_stage=None)
    with ledger.stage("timed_samples"):
        pass
    ledger.emit(LedgerEvent.RUN_END, outcome="cpu_fallback")
    ledger.close()
    assert perfview.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "outcome: cpu_fallback (after run_fail: wedged)" in out
    assert "outcome: FAILED" not in out


def test_errors_cleanly_on_bad_inputs(tmp_path, capsys):
    missing = tmp_path / "missing.jsonl"
    assert perfview.main([str(missing)]) == 2
    assert "perfview:" in capsys.readouterr().err
    scalar = tmp_path / "scalar.json"
    scalar.write_text("42")
    assert perfview.main([str(scalar)]) == 2
    assert "not a bench metric artifact" in capsys.readouterr().err
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    assert perfview.main([str(bad)]) == 2
    assert "invalid JSON" in capsys.readouterr().err


def test_trajectory_flags_collective_count_drift(tmp_path, capsys):
    # Rounds carrying bench.py's hlo_audit table are diffed pairwise: a
    # collective-count change between audited rounds flags the LATER point
    # hlo-drift; un-audited (or errored) rounds in between neither flag
    # nor reset the comparison baseline.
    def audit(hot):
        return {"sharded_wave": {"collectives": 10 + hot,
                                 "hot_loop_collectives": hot,
                                 "temp_bytes": 1000, "donation_dropped": 0}}

    points = {
        "BENCH_r11.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit(hot=2)},
        "BENCH_r12.json": {"metric": "m", "value": 1.0, "platform": "cpu"},
        "BENCH_r13.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": {"error": "no devices"}},
        "BENCH_r14.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit(hot=3)},
        "BENCH_r15.json": {"metric": "m", "value": 1.0, "platform": "cpu",
                           "hlo_audit": audit(hot=3)},
    }
    paths = []
    for name, data in points.items():
        p = tmp_path / name
        p.write_text(json.dumps(data))
        paths.append(str(p))
    assert perfview.main(paths) == 0
    out = capsys.readouterr().out
    lines = {line.split()[0]: line for line in out.splitlines()
             if line.startswith("BENCH_r1")}
    assert "hlo-drift" not in lines["BENCH_r11"]  # nothing earlier to diff
    assert "live" in lines["BENCH_r12"]  # un-audited round: no flag
    assert "live" in lines["BENCH_r13"]  # errored audit: no flag
    assert "hlo-drift" in lines["BENCH_r14"]  # 2 -> 3 vs r11's baseline
    assert "hlo-drift" not in lines["BENCH_r15"]  # stable vs r14
