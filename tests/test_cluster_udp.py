"""Full cluster over the hybrid TCP+UDP transport: alerts and votes ride
datagrams, joins and probes ride TCP."""

import asyncio
import functools
import random

from rapid_tpu.messaging.udp import ONEWAY_TYPES, UdpHybridClient, UdpHybridServer
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint, FastRoundPhase2bMessage, Response

from helpers import wait_until

BASE_PORT = 37200


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=60)

        asyncio.run(with_timeout())

    return wrapper


def fast_settings() -> Settings:
    s = Settings()
    s.batching_window_ms = 20
    s.failure_detector_interval_ms = 50
    s.rpc_timeout_ms = 500
    s.rpc_join_timeout_ms = 2000
    s.rpc_probe_timeout_ms = 200
    s.consensus_fallback_base_delay_ms = 2000
    return s


def ep(i: int) -> Endpoint:
    return Endpoint("127.0.0.1", BASE_PORT + i)



@async_test
async def test_six_nodes_over_hybrid_udp_with_failure():
    settings = fast_settings()
    fd = StaticFailureDetectorFactory()

    sent_udp = []

    class CountingClient(UdpHybridClient):
        async def send_best_effort(self, remote, request):
            if isinstance(request, ONEWAY_TYPES):
                sent_udp.append(type(request).__name__)
            return await super().send_best_effort(remote, request)

    clusters = [
        await Cluster.start(ep(0), settings=settings, client=CountingClient(ep(0), settings),
                            server=UdpHybridServer(ep(0)), fd_factory=fd, rng=random.Random(0))
    ]
    for i in range(1, 6):
        clusters.append(
            await Cluster.join(ep(0), ep(i), settings=settings,
                               client=CountingClient(ep(i), settings),
                               server=UdpHybridServer(ep(i)), fd_factory=fd,
                               rng=random.Random(i))
        )
    try:
        assert await wait_until(
            lambda: all(c.membership_size == 6 for c in clusters)
            and len({tuple(c.membership) for c in clusters}) == 1
        )
        # Alerts and fast-round votes actually traveled as datagrams.
        assert "BatchedAlertMessage" in sent_udp
        assert "FastRoundPhase2bMessage" in sent_udp

        victim = clusters[4]
        await victim.shutdown()
        fd.add_failed_nodes([victim.listen_address])
        survivors = [c for c in clusters if c is not victim]
        assert await wait_until(lambda: all(c.membership_size == 5 for c in survivors))
        assert all(victim.listen_address not in c.membership for c in survivors)
    finally:
        await asyncio.gather(*(c.shutdown() for c in clusters), return_exceptions=True)


@async_test
async def test_hybrid_point_to_point_roundtrip_and_datagram_oneway():
    # NettyClientServerTest analog for the alternate transport: a one-way
    # consensus message genuinely arrives as a datagram (proven by the
    # client holding NO TCP connection when it lands — a TCP fallback would
    # have created one), then a request/response round-trip rides TCP.
    from rapid_tpu.types import ProbeMessage

    s = Settings()
    a, b = Endpoint("127.0.0.1", 37290), Endpoint("127.0.0.1", 37291)
    received = []
    server = UdpHybridServer(b)
    server.set_membership_service(_Recorder(received))
    await server.start()
    client = UdpHybridClient(a, s)
    try:
        assert FastRoundPhase2bMessage in ONEWAY_TYPES  # travels as datagram
        # Datagram FIRST, before any TCP traffic exists.
        client.send_nowait(
            b, FastRoundPhase2bMessage(sender=a, configuration_id=1, endpoints=(a,))
        )
        assert await wait_until(
            lambda: any(isinstance(r, FastRoundPhase2bMessage) for r in received)
        )
        # Delivery used the datagram path: no TCP connection was ever made
        # (the silent TCP fallback would have cached one).
        assert not client._connections
        # Round-trip over the reliable path.
        resp = await client.send(b, ProbeMessage(sender=a))
        assert isinstance(resp, Response)
        assert any(isinstance(r, ProbeMessage) for r in received)
        assert client._connections  # the round-trip DID use TCP
    finally:
        await client.shutdown()
        await server.shutdown()


class _Recorder:
    """Recording membership-service stub shared by the transport tests."""

    def __init__(self, received):
        self.received = received

    async def handle_message(self, request):
        self.received.append(request)
        return Response()


@async_test
async def test_udp_server_survives_garbage_datagrams():
    # Datagram-level fault isolation: undecodable datagrams (random bytes,
    # a truncated frame, an unknown tag) are dropped without disturbing the
    # endpoint — a real one-way message sent afterwards still processes.
    s = Settings()
    a, b = Endpoint("127.0.0.1", 37391), Endpoint("127.0.0.1", 37392)
    received = []
    server = UdpHybridServer(b)
    server.set_membership_service(_Recorder(received))
    await server.start()
    client = UdpHybridClient(a, s)
    loop = asyncio.get_running_loop()
    hostile, _ = await loop.create_datagram_endpoint(
        asyncio.DatagramProtocol, remote_addr=(b.hostname, b.port)
    )
    try:
        rx_before = server.stats.msgs_rx
        for junk in (b"\xff" * 40, b"", b"\x00", b"\xfe" + b"A" * 200):
            hostile.sendto(junk)
        # The server has SEEN the junk (rx counts every datagram) before we
        # assert it still works; empty datagrams may be dropped by the OS,
        # so require only the non-empty ones.
        assert await wait_until(lambda: server.stats.msgs_rx >= rx_before + 3)

        client.send_nowait(
            b, FastRoundPhase2bMessage(sender=a, configuration_id=1, endpoints=(a,))
        )
        assert await wait_until(
            lambda: any(isinstance(r, FastRoundPhase2bMessage) for r in received)
        )
    finally:
        hostile.close()
        await client.shutdown()
        await server.shutdown()
