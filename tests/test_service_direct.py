"""Driving MembershipService directly (reference: MessagingTest.java):
join phase-1 semantics against large views, the ClientDelayer latch fixture,
and service-level fast-round quorum behavior."""

import asyncio
import functools
import random

from rapid_tpu.messaging.inprocess import (
    ClientDelayer,
    InProcessClient,
    InProcessNetwork,
    InProcessServer,
)
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
from rapid_tpu.protocol.service import MembershipService
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.settings import Settings
from rapid_tpu.protocol.events import ClusterEvents
from rapid_tpu.types import (
    Endpoint,
    FastRoundPhase2bMessage,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    NodeId,
    PreJoinMessage,
    ProbeMessage,
)

from helpers import wait_until


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=30)

        asyncio.run(with_timeout())

    return wrapper


def make_service(n_members, k=10, h=9, l=4, base_port=40000, loopback=False):
    """A single MembershipService with a synthetic n-member view
    (MessagingTest.java:151+'s 1000-node configuration scenario). With
    ``loopback`` a server is registered for the service's own address (so it
    hears its own broadcasts) and returned as a third element — the caller
    must ``await server.start()``/``shutdown()`` and ``service.start()``."""
    settings = Settings()
    settings.k, settings.h, settings.l = k, h, l
    settings.batching_window_ms = 20
    network = InProcessNetwork()
    my_addr = Endpoint("127.0.0.1", base_port)
    endpoints = [Endpoint("127.0.0.1", base_port + i) for i in range(n_members)]
    node_ids = [NodeId(0, i) for i in range(n_members)]
    view = MembershipView(k, node_ids=node_ids, endpoints=endpoints)
    service = MembershipService(
        my_addr=my_addr,
        cut_detector=MultiNodeCutDetector(k, h, l),
        view=view,
        settings=settings,
        client=InProcessClient(network, my_addr, settings),
        fd_factory=StaticFailureDetectorFactory(),
        rng=random.Random(0),
    )
    if loopback:
        server = InProcessServer(network, my_addr)
        server.set_membership_service(service)
        return service, endpoints, server
    return service, endpoints



@async_test
async def test_prejoin_against_thousand_node_view():
    service, endpoints = make_service(1000)
    joiner = Endpoint("127.0.0.1", 50000)
    response = await service.handle_message(PreJoinMessage(sender=joiner, node_id=NodeId(7, 7)))
    assert isinstance(response, JoinResponse)
    assert response.status_code == JoinStatusCode.SAFE_TO_JOIN
    assert len(response.endpoints) == 10  # K expected observers
    assert all(ep in endpoints for ep in response.endpoints)
    # Rejections: hostname present / uuid seen.
    response = await service.handle_message(
        PreJoinMessage(sender=endpoints[5], node_id=NodeId(7, 8))
    )
    assert response.status_code == JoinStatusCode.HOSTNAME_ALREADY_IN_RING
    response = await service.handle_message(
        PreJoinMessage(sender=joiner, node_id=NodeId(0, 123))
    )
    assert response.status_code == JoinStatusCode.UUID_ALREADY_IN_RING
    await service.shutdown()


@async_test
async def test_service_level_fast_round_quorum():
    # FastPaxosWithoutFallbackTests at the service boundary: hand-built
    # votes through handle_message decide exactly at N - floor((N-1)/4).
    n = 102
    service, endpoints = make_service(n)
    config_id = service.view.configuration_id
    victim = endpoints[50]
    proposal = (victim,)
    quorum = n - (n - 1) // 4
    for i in range(quorum - 1):
        await service.handle_message(
            FastRoundPhase2bMessage(sender=endpoints[i], configuration_id=config_id,
                                    endpoints=proposal)
        )
        assert service.membership_size == n  # not yet
    # Note: the decision path calls ring_delete for the victim.
    await service.handle_message(
        FastRoundPhase2bMessage(sender=endpoints[quorum - 1], configuration_id=config_id,
                                endpoints=proposal)
    )
    assert service.membership_size == n - 1
    assert victim not in service.membership
    await service.shutdown()


@async_test
async def test_decision_with_unknown_joiner_triggers_rejoin_not_corruption():
    # A consensus decision can name a joiner whose UP alert this node never
    # received (alert broadcasts are best-effort; the UDP transport ships
    # them as droppable datagrams). The service must apply NOTHING and signal
    # KICKED for rejoin — not KeyError mid-mutation (the reference NPEs,
    # MembershipService.java:401-404).
    n = 20
    service, endpoints = make_service(n)
    config_id = service.view.configuration_id
    unknown_joiner = Endpoint("127.0.0.1", 59999)  # no UP alert ever seen
    proposal = (unknown_joiner,)
    kicked = []
    service.register_subscription(ClusterEvents.KICKED, kicked.append)
    quorum = n - (n - 1) // 4
    for i in range(quorum):
        await service.handle_message(
            FastRoundPhase2bMessage(sender=endpoints[i], configuration_id=config_id,
                                    endpoints=proposal)
        )
    # View untouched: same config, same size, joiner absent.
    assert service.membership_size == n
    assert unknown_joiner not in service.membership
    assert service.view.configuration_id == config_id
    # Recovery signalled with the stale configuration's details.
    assert len(kicked) == 1
    assert kicked[0].configuration_id == config_id
    assert service.metrics.counters["decision_missing_joiner_uuid"] == 1
    await service.shutdown()


@async_test
async def test_client_delayer_latch():
    # The ClientDelayer fixture (MessageDropInterceptor.java:51-73): messages
    # of a type are held until the latch opens.
    network = InProcessNetwork()
    target_addr = Endpoint("127.0.0.1", 41000)
    server = InProcessServer(network, target_addr)
    received = []

    class Recorder:
        async def handle_message(self, request):
            received.append(request)
            from rapid_tpu.types import Response

            return Response()

    server.set_membership_service(Recorder())
    await server.start()

    client = InProcessClient(network, Endpoint("127.0.0.1", 41001))
    delayer = ClientDelayer(ProbeMessage)
    client.delayers.append(delayer)

    probe_task = asyncio.ensure_future(
        client.send_best_effort(target_addr, ProbeMessage(sender=target_addr))
    )
    await asyncio.sleep(0.05)
    assert received == []  # held by the latch
    delayer.open()
    await probe_task
    assert len(received) == 1
    await client.shutdown()
    await server.shutdown()


@async_test
async def test_lost_phase2_response_recovers_via_config_minus_one():
    # Cluster.java:374-381's HOSTNAME_ALREADY_IN_RING recovery: a joiner was
    # admitted by consensus but its phase-2 JoinResponse was lost. On retry,
    # phase 1 answers HOSTNAME_ALREADY_IN_RING, and a phase-2 JoinMessage
    # with configuration_id = -1 (never a real config id) must stream the
    # full configuration back (MembershipService.java:255-286: host AND
    # identifier present).
    n = 8
    service, endpoints, server = make_service(n, base_port=43000, loopback=True)
    await server.start()
    await service.start()  # arms the alert batcher
    k = service.settings.k

    joiner = Endpoint("127.0.0.1", 58000)
    joiner_id = NodeId(11, 22)
    config_id = service.view.configuration_id

    # Phase 2 under the CORRECT config: consensus admits the joiner (every
    # member's fast votes arrive), but pretend the joiner never saw the
    # response future resolve.
    pending = service.handle_message(
        JoinMessage(sender=joiner, node_id=joiner_id, ring_numbers=tuple(range(k)),
                    configuration_id=config_id)
    )
    fut = asyncio.ensure_future(pending)
    # The alert batch must flush and announce the cut (recording the
    # joiner's UUID) before any decision applies.
    assert await wait_until(lambda: service._announced_proposal)
    for i in range(n):
        await service.handle_message(
            FastRoundPhase2bMessage(sender=endpoints[i],
                                    configuration_id=config_id,
                                    endpoints=(joiner,))
        )
    await asyncio.wait_for(fut, timeout=5)
    assert service.membership_size == n + 1

    # Retry path: phase 1 now reports the hostname as already present...
    phase1 = await service.handle_message(PreJoinMessage(sender=joiner, node_id=joiner_id))
    assert phase1.status_code == JoinStatusCode.HOSTNAME_ALREADY_IN_RING
    # ...and phase 2 with config -1 streams the configuration.
    response = await service.handle_message(
        JoinMessage(sender=joiner, node_id=joiner_id, ring_numbers=(0,),
                    configuration_id=-1)
    )
    assert response.status_code == JoinStatusCode.SAFE_TO_JOIN
    assert joiner in response.endpoints
    assert joiner_id in response.identifiers
    await service.shutdown()
    await server.shutdown()


@async_test
async def test_cancelled_background_loops_actually_exit():
    """Cancellation hygiene (the taskflow analyzer's contract, enforced
    end-to-end): every background loop the service arms — alert batcher,
    redelivery, config sync, failure detectors — must EXIT when cancelled,
    not absorb the CancelledError and keep looping (the liveness loops
    catch broad Exception by design, so their explicit CancelledError
    re-raise is load-bearing; if one swallowed it, shutdown would hang on
    the gather forever)."""
    service, _ = make_service(8)
    await service.start()
    tasks = list(service._background_tasks) + list(service._fd_tasks)
    assert tasks, "service.start() armed no background loops"
    for task in tasks:
        task.cancel()
    done, pending = await asyncio.wait(tasks, timeout=5)
    assert not pending, f"loops survived cancellation: {pending}"
    for task in done:
        assert task.cancelled() or task.exception() is None
    await service.shutdown()
