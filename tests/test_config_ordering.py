"""Property test for the configuration-ordering rule behind config catch-up.

Configuration ids are hash folds — unordered — so the catch-up path orders
two configurations structurally (``view.identifiers_seen`` docstring;
``service._apply_catch_up_response``):

    newer(B over A)  ⇔  ids(B) ⊃ ids(A)
                        ∨ (ids(B) = ids(A) ∧ endpoints(B) ⊂ endpoints(A))

This is sound because identifier history is append-only along the decided
chain (``ring_delete`` never removes identifiers) and equal-identifier
stretches of the chain are remove-only. The property pinned here, over
randomized decided chains of joins and crashes: for ANY two configurations
A (earlier) and B (later) on the chain, the rule says B is newer than A
and never the reverse — i.e. the structural predicate recovers the chain
order exactly, with no false positives in either direction. A node
applying only "newer" configurations can therefore never be rolled back by
a stale peer, no matter which snapshots it is offered in which order.
"""

import pytest

pytest.importorskip("hypothesis")  # property tests need it; the rest of the suite doesn't
from hypothesis import given, settings, strategies as st

from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.types import Endpoint, NodeId


def is_newer(candidate, current) -> bool:
    """The exact predicate _apply_catch_up_response evaluates, over
    (identifier-set, endpoint-set) snapshot pairs."""
    cand_ids, cand_eps = candidate
    cur_ids, cur_eps = current
    return cand_ids > cur_ids or (cand_ids == cur_ids and cand_eps < cur_eps)


@st.composite
def decided_chain(draw):
    """A random decided chain: bootstrap membership, then a sequence of
    join/crash steps (each a committed view change), snapshotting
    (identifiers_seen, endpoint set) after every configuration."""
    n0 = draw(st.integers(min_value=2, max_value=6))
    view = MembershipView(3)
    next_id = 0
    for i in range(n0):
        view.ring_add(Endpoint(f"n{i}", 4000 + i), NodeId(0, next_id))
        next_id += 1
    next_port = n0
    snapshots = [(view.identifiers_seen(), frozenset(view.ring(0)))]
    steps = draw(st.lists(st.booleans(), min_size=1, max_size=12))
    for is_join in steps:
        if is_join or view.membership_size <= 2:
            view.ring_add(Endpoint(f"n{next_port}", 4000 + next_port), NodeId(0, next_id))
            next_port += 1
            next_id += 1
        else:
            victim_idx = draw(
                st.integers(min_value=0, max_value=view.membership_size - 1)
            )
            view.ring_delete(view.ring(0)[victim_idx])
        snapshots.append((view.identifiers_seen(), frozenset(view.ring(0))))
    return snapshots


@settings(max_examples=200, deadline=None)
@given(decided_chain())
def test_ordering_rule_recovers_chain_order_exactly(snapshots):
    for i in range(len(snapshots)):
        for j in range(len(snapshots)):
            if i < j:
                assert is_newer(snapshots[j], snapshots[i]), (
                    f"later config {j} not recognized as newer than {i}"
                )
                assert not is_newer(snapshots[i], snapshots[j]), (
                    f"rollback: earlier config {i} claimed newer than {j}"
                )
            elif i == j:
                assert not is_newer(snapshots[i], snapshots[j])


@settings(max_examples=100, deadline=None)
@given(decided_chain(), decided_chain())
def test_foreign_chain_never_claims_newer_without_identifier_evidence(a, b):
    # Two INDEPENDENT chains (disjoint histories do not share identifiers
    # here only by construction accident — NodeId low-words overlap across
    # draws, which is exactly the hostile case): a foreign snapshot may only
    # be accepted over ours if its identifier history covers ours entirely.
    # Whatever the draw, the predicate must stay antisymmetric: no pair is
    # "newer" in both directions (a cycle would let two nodes adopt each
    # other's configs forever).
    for sa in a:
        for sb in b:
            assert not (is_newer(sa, sb) and is_newer(sb, sa))
