"""Protocol-level delivery liveness under droppable transports.

The reference protocol fires every broadcast exactly once and stays live
because its transport guarantees delivery (``Retries.java:43-90``; channel
retry wrapper ``GrpcClient.java:106-115``). The transports here may drop
(the UDP hybrid ships one-way traffic as datagrams), so the same guarantee
is re-established at the protocol level instead:

- undecided consensus re-arms: the fallback timer re-offers the fast-round
  vote and escalates one classic round per tick (``fast_paxos.py``), with
  coordinator state reset between rounds (``paxos.py``);
- alert batches are re-broadcast while their cut is unresolved;
- a node with evidence (traffic stamped with a configuration id it never
  inhabited) or suspicion (stuck proposal / unresolved cut / unappliable
  decision) of staleness pulls the current configuration from a peer over
  the reliable request/response path and adopts it if ahead.

These tests pin each mechanism in isolation; ``tests/test_udp_loss.py``
pins the end-to-end envelope under seeded datagram loss.
"""

import asyncio
import functools
import random

from rapid_tpu.messaging.inprocess import (
    InProcessClient,
    InProcessNetwork,
    InProcessServer,
)
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
from rapid_tpu.protocol.events import ClusterEvents
from rapid_tpu.protocol.fast_paxos import FastPaxos, fast_paxos_quorum
from rapid_tpu.protocol.paxos import Paxos, node_index_of
from rapid_tpu.protocol.service import MembershipService
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    Endpoint,
    FastRoundPhase2bMessage,
    NodeId,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Rank,
)
from rapid_tpu.utils.clock import ManualClock

from helpers import wait_until


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=30)

        asyncio.run(with_timeout())

    return wrapper


def ep(i: int) -> Endpoint:
    return Endpoint("127.0.0.1", 42000 + i)


# ---------------------------------------------------------------------------
# paxos.py: coordinator state must reset between escalating rounds
# ---------------------------------------------------------------------------


def test_stale_promises_do_not_satisfy_a_later_round():
    # n=5: majority is 3. Two promises collected at round 2 plus one at
    # round 4 must NOT look like a majority for round 4.
    broadcasts = []
    paxos = Paxos(ep(0), 7, 5, broadcasts.append, lambda r, m: None, lambda v: None)

    paxos.start_phase1a(2)
    rank2 = Rank(2, node_index_of(ep(0)))
    value = (ep(9),)
    for sender in (ep(1), ep(2)):
        paxos.handle_phase1b(
            Phase1bMessage(sender=sender, configuration_id=7, rnd=rank2,
                           vrnd=Rank(1, 1), vval=value)
        )
    assert not any(isinstance(b, Phase2aMessage) for b in broadcasts)

    paxos.start_phase1a(4)  # escalation discards round-2 promises
    rank4 = Rank(4, node_index_of(ep(0)))
    paxos.handle_phase1b(
        Phase1bMessage(sender=ep(3), configuration_id=7, rnd=rank4,
                       vrnd=Rank(1, 1), vval=value)
    )
    assert not any(isinstance(b, Phase2aMessage) for b in broadcasts), (
        "2 stale round-2 promises + 1 round-4 promise must not reach the "
        "round-4 majority"
    )
    for sender in (ep(1), ep(2)):
        paxos.handle_phase1b(
            Phase1bMessage(sender=sender, configuration_id=7, rnd=rank4,
                           vrnd=Rank(1, 1), vval=value)
        )
    phase2a = [b for b in broadcasts if isinstance(b, Phase2aMessage)]
    assert len(phase2a) == 1 and phase2a[0].vval == value


def test_escalated_round_repicks_value():
    # cval resets on escalation: the round-4 quorum's vvals decide the pick,
    # not a leftover from round 2.
    broadcasts = []
    paxos = Paxos(ep(0), 7, 3, broadcasts.append, lambda r, m: None, lambda v: None)
    paxos.start_phase1a(2)
    rank2 = Rank(2, node_index_of(ep(0)))
    for sender in (ep(1), ep(2)):
        paxos.handle_phase1b(
            Phase1bMessage(sender=sender, configuration_id=7, rnd=rank2,
                           vrnd=Rank(1, 1), vval=(ep(8),))
        )
    assert paxos.cval == (ep(8),)
    paxos.start_phase1a(3)
    assert paxos.cval == ()
    rank3 = Rank(3, node_index_of(ep(0)))
    for sender in (ep(1), ep(2)):
        paxos.handle_phase1b(
            Phase1bMessage(sender=sender, configuration_id=7, rnd=rank3,
                           vrnd=Rank(2, 2), vval=(ep(9),))
        )
    assert paxos.cval == (ep(9),)


# ---------------------------------------------------------------------------
# fast_paxos.py: the fallback is a recurring liveness tick
# ---------------------------------------------------------------------------


def test_fallback_rearms_and_escalates_until_decided():
    clock = ManualClock()
    broadcasts = []
    decided = []
    fp = FastPaxos(
        my_addr=ep(0), configuration_id=7, membership_size=3,
        broadcast_fn=broadcasts.append, send_fn=lambda r, m: None,
        on_decide=decided.append, clock=clock,
        consensus_fallback_base_delay_ms=100, rng=random.Random(0),
    )
    fp.propose((ep(2),), recovery_delay_ms=100)
    votes = [b for b in broadcasts if isinstance(b, FastRoundPhase2bMessage)]
    assert len(votes) == 1

    clock.advance_ms(150)  # first tick: re-offer vote, classic round 2
    # Re-arm delays are expovariate with mean ~N*1000ms over the base delay;
    # 30 s of simulated time yields several more ticks.
    clock.advance_ms(30_000)
    votes = [b for b in broadcasts if isinstance(b, FastRoundPhase2bMessage)]
    phase1a = [b for b in broadcasts if isinstance(b, Phase1aMessage)]
    assert len(votes) >= 3, "undecided vote must be re-broadcast every tick"
    rounds = [m.rank.round for m in phase1a]
    assert rounds[0] == 2 and rounds == sorted(rounds) and len(set(rounds)) >= 2, (
        f"classic rounds must escalate from 2, got {rounds}"
    )

    # Decision cancels the re-arm: no further traffic.
    quorum = fast_paxos_quorum(3)
    for i in range(quorum):
        fp.handle_message(
            FastRoundPhase2bMessage(sender=ep(i), configuration_id=7, endpoints=(ep(2),))
        )
    assert decided == [(ep(2),)]
    n_before = len(broadcasts)
    clock.advance_ms(60_000)
    assert len(broadcasts) == n_before


def test_cancel_fallback_stops_rearming():
    clock = ManualClock()
    broadcasts = []
    fp = FastPaxos(
        my_addr=ep(0), configuration_id=7, membership_size=3,
        broadcast_fn=broadcasts.append, send_fn=lambda r, m: None,
        on_decide=lambda v: None, clock=clock,
        consensus_fallback_base_delay_ms=100, rng=random.Random(0),
    )
    fp.propose((ep(2),), recovery_delay_ms=100)
    fp.cancel_fallback()
    n_before = len(broadcasts)
    clock.advance_ms(60_000)
    assert len(broadcasts) == n_before


# ---------------------------------------------------------------------------
# service.py: config catch-up
# ---------------------------------------------------------------------------


def build_service(network, my_index, endpoints, node_ids, settings=None,
                  metadata=None, clock=None):
    """A MembershipService over InProcessNetwork with its server registered,
    identity plumbed (node_id enables the catch-up path)."""
    settings = settings or Settings()
    settings.batching_window_ms = 20
    my_addr = endpoints[my_index]
    view = MembershipView(settings.k, node_ids=node_ids, endpoints=endpoints)
    service = MembershipService(
        my_addr=my_addr,
        cut_detector=MultiNodeCutDetector(settings.k, settings.h, settings.l),
        view=view,
        settings=settings,
        client=InProcessClient(network, my_addr, settings),
        fd_factory=StaticFailureDetectorFactory(),
        metadata_map=metadata,
        rng=random.Random(my_index),
        node_id=node_ids[my_index],
        clock=clock,
    )
    server = InProcessServer(network, my_addr)
    server.set_membership_service(service)
    return service, server


@async_test
async def test_evidence_of_unknown_config_triggers_catch_up():
    # A (5-member view) receives a consensus vote stamped with a config id it
    # never inhabited, from peer e1 whose view is one join ahead. A pulls
    # from e1 over the reliable path and installs the newer configuration.
    network = InProcessNetwork()
    ids = [NodeId(0, i) for i in range(6)]
    old_eps, new_eps = [ep(i) for i in range(5)], [ep(i) for i in range(6)]

    stale, stale_server = build_service(network, 0, old_eps, ids[:5])
    ahead, ahead_server = build_service(network, 1, new_eps, ids)
    await stale_server.start()
    await ahead_server.start()
    await stale.start()
    try:
        assert stale.membership_size == 5
        evidence = FastRoundPhase2bMessage(
            sender=ahead.my_addr,
            configuration_id=ahead.view.configuration_id,
            endpoints=(ep(9),),
        )
        await stale.handle_message(evidence)
        assert await wait_until(lambda: stale.membership_size == 6)
        assert stale.view.configuration_id == ahead.view.configuration_id
        assert stale.metrics.counters["config_catch_ups"] == 1
        assert ep(5) in stale.membership
    finally:
        await stale_server.shutdown()
        await ahead_server.shutdown()
        await stale.shutdown()
        await ahead.shutdown()


@async_test
async def test_catch_up_never_adopts_an_older_configuration():
    # The pull target may itself be stale: a fetched config whose identifier
    # history is NOT a strict superset (nor an equal-id endpoint subset)
    # must be ignored — config ids are hashes and carry no order.
    network = InProcessNetwork()
    ids = [NodeId(0, i) for i in range(6)]
    new_eps, old_eps = [ep(i) for i in range(6)], [ep(i) for i in range(5)]

    current, current_server = build_service(network, 0, new_eps, ids)
    behind, behind_server = build_service(network, 1, old_eps, ids[:5])
    await current_server.start()
    await behind_server.start()
    await current.start()
    try:
        config_before = current.view.configuration_id
        evidence = FastRoundPhase2bMessage(
            sender=behind.my_addr,
            configuration_id=behind.view.configuration_id,
            endpoints=(ep(9),),
        )
        await current.handle_message(evidence)
        # Give the catch-up task time to complete (and be ignored).
        await wait_until(
            lambda: not current._catch_up_inflight and not current._catch_up_tasks,
            timeout_s=5,
        )
        assert current.membership_size == 6
        assert current.view.configuration_id == config_before
        assert current.metrics.counters["config_catch_ups"] == 0
    finally:
        await current_server.shutdown()
        await behind_server.shutdown()
        await current.shutdown()
        await behind.shutdown()


@async_test
async def test_eviction_requires_proof_not_ambiguous_answers():
    # "You are not in my view" alone is ambiguous (the peer may be stuck in
    # a configuration predating our join) and must NEVER convict — no matter
    # how many peers say it. Eviction is concluded only from verifiable
    # proof: a view whose identifier history covers ours (it can only have
    # seen our identifier if it inhabited a configuration we were in) yet
    # whose endpoints exclude us.
    network = InProcessNetwork()
    my_ids = [NodeId(0, i) for i in range(3)]
    my_eps = [ep(i) for i in range(3)]
    node, node_server = build_service(network, 0, my_eps, my_ids)
    node.settings.config_sync_interval_ms = 1  # allow rapid re-pulls
    # Three stale peers whose views never contained this node or its id.
    stale_peers = []
    for i in (1, 2, 3):
        peer_ids = [NodeId(9, 100 * i + j) for j in range(2)]
        peer_eps = [ep(100 + i), ep(200 + i)]
        service, server = build_service(network, 0, peer_eps, peer_ids)
        stale_peers.append((service, server))
        await server.start()
    await node_server.start()
    await node.start()
    kicked = []
    node.register_subscription(ClusterEvents.KICKED, kicked.append)
    try:
        for peer, _ in stale_peers:
            await node.handle_message(
                FastRoundPhase2bMessage(
                    sender=peer.my_addr,
                    configuration_id=peer.view.configuration_id,
                    endpoints=(ep(9),),
                )
            )
            assert await wait_until(
                lambda: not node._catch_up_inflight and not node._catch_up_tasks,
                timeout_s=5,
            )
            await asyncio.sleep(0.01)
        assert not kicked, "ambiguous absent-from-view answers must not convict"
        assert node.metrics.counters["kicked"] == 0

        # A peer whose view DID remove us (it holds our identifier in its
        # append-only history, endpoints exclude us) proves eviction: one
        # answer convicts, immediately.
        prover, prover_server = build_service(
            network, 0, [ep(1), ep(2)], my_ids  # our id n0 seen; e0 removed
        )
        await prover_server.start()
        try:
            await node.handle_message(
                FastRoundPhase2bMessage(
                    sender=prover.my_addr,
                    configuration_id=prover.view.configuration_id,
                    endpoints=(ep(9),),
                )
            )
            assert await wait_until(lambda: len(kicked) == 1)
            assert node.metrics.counters["kicked"] == 1
        finally:
            await prover_server.shutdown()
            await prover.shutdown()
    finally:
        await node_server.shutdown()
        await node.shutdown()
        for service, server in stale_peers:
            await server.shutdown()
            await service.shutdown()


@async_test
async def test_eviction_proof_rules():
    # Direct pin of the proof check: payload-less CONFIG_CHANGED and
    # non-superset payloads never convict; a superset-without-us payload
    # convicts exactly once (latched).
    from rapid_tpu.types import JoinResponse, JoinStatusCode

    network = InProcessNetwork()
    ids = [NodeId(0, i) for i in range(4)]
    eps = [ep(i) for i in range(4)]
    service, server = build_service(network, 0, eps, ids)
    kicked = []
    service.register_subscription(ClusterEvents.KICKED, kicked.append)
    try:
        plain = JoinResponse(
            sender=eps[1], status_code=JoinStatusCode.CONFIG_CHANGED,
            configuration_id=123,
        )
        not_superset = JoinResponse(  # stale id space: never saw our ids
            sender=eps[1], status_code=JoinStatusCode.CONFIG_CHANGED,
            configuration_id=124, endpoints=(eps[1], eps[2]),
            identifiers=(NodeId(9, 9),),
        )
        proof = JoinResponse(  # full history, endpoints exclude us
            sender=eps[1], status_code=JoinStatusCode.CONFIG_CHANGED,
            configuration_id=125, endpoints=(eps[1], eps[2], eps[3]),
            identifiers=tuple(ids) + (NodeId(7, 7),),
        )
        service._apply_catch_up_response(eps[1], plain)
        service._apply_catch_up_response(eps[2], plain)
        service._apply_catch_up_response(eps[3], plain)
        service._apply_catch_up_response(eps[1], not_superset)
        assert not kicked, "ambiguous/unverifiable answers must not convict"
        service._apply_catch_up_response(eps[1], proof)
        assert len(kicked) == 1
        # Latched: further proof answers must not re-fire KICKED.
        service._apply_catch_up_response(eps[2], proof)
        assert len(kicked) == 1
        assert service.metrics.counters["kicked"] == 1
    finally:
        await server.shutdown()
        await service.shutdown()


def test_engine_rejects_java_topology_at_the_key_seam():
    # The engine's u64 keyspace cannot represent java-compat signed ring
    # order; pairing them must fail loudly at from_endpoints, not diverge.
    import pytest

    from rapid_tpu.models.virtual_cluster import VirtualCluster

    with pytest.raises(ValueError, match="native topology"):
        VirtualCluster.from_endpoints([ep(0), ep(1), ep(2)], topology="java")


@async_test
async def test_decision_missing_uuid_recovers_by_pull_not_rejoin():
    # A consensus decision names a joiner whose every UP alert this node
    # lost. Round-4 behavior: apply nothing, signal KICKED, force a rejoin.
    # Now: apply nothing and pull the decided configuration — identifiers
    # included — from a peer that applied it. No KICKED, no rejoin.
    network = InProcessNetwork()
    ids = [NodeId(0, i) for i in range(5)]
    eps = [ep(i) for i in range(5)]
    joiner, joiner_id = ep(5), NodeId(0, 5)

    settings = Settings()
    settings.config_sync_interval_ms = 50  # fast retry until a peer has it
    node, node_server = build_service(network, 0, eps, ids, settings=settings)
    # Peer at e1 already applied the decision: view includes the joiner.
    applied, applied_server = build_service(network, 1, eps + [joiner], ids + [joiner_id])
    await node_server.start()
    await applied_server.start()
    await node.start()
    kicked = []
    node.register_subscription(ClusterEvents.KICKED, kicked.append)
    try:
        config_id = node.view.configuration_id
        quorum = fast_paxos_quorum(5)
        for i in range(quorum):
            await node.handle_message(
                FastRoundPhase2bMessage(
                    sender=eps[i], configuration_id=config_id, endpoints=(joiner,)
                )
            )
        # The decision could not be applied locally...
        assert node.metrics.counters["decision_missing_joiner_uuid"] == 1
        # ...but the sync loop pulls it from a peer instead of rejoining.
        assert await wait_until(lambda: node.membership_size == 6, timeout_s=10)
        assert joiner in node.membership
        assert node.view.configuration_id == applied.view.configuration_id
        assert not kicked
        assert node.metrics.counters["kicked"] == 0
    finally:
        await node_server.shutdown()
        await applied_server.shutdown()
        await node.shutdown()
        await applied.shutdown()


@async_test
async def test_stale_sender_traffic_draws_a_config_beacon():
    # A member that missed a decision keeps emitting old-config traffic
    # (its liveness tick re-offers votes). An up-to-date receiver — for whom
    # those config ids are all known history — answers with a config
    # BEACON: a semantically inert self-UP alert batch stamped with the
    # current config id, which the stale sender treats as evidence of an
    # unknown configuration and pulls. End state: the stale member catches
    # up without anyone pushing configuration state over best-effort lanes.
    network = InProcessNetwork()
    ids = [NodeId(0, i) for i in range(5)]
    eps = [ep(i) for i in range(5)]
    current, current_server = build_service(network, 1, eps, ids)
    stale, stale_server = build_service(network, 0, eps, ids)
    await current_server.start()
    await stale_server.start()
    await stale.start()
    try:
        old_config = current.view.configuration_id
        assert old_config == stale.view.configuration_id
        # Drive a real crash decision at `current` only: quorum fast-round
        # votes naming an existing member (no joiner UUID needed).
        victim = eps[4]
        quorum = fast_paxos_quorum(5)
        for i in range(quorum):
            await current.handle_message(
                FastRoundPhase2bMessage(
                    sender=eps[i], configuration_id=old_config, endpoints=(victim,)
                )
            )
        assert current.membership_size == 4
        assert stale.membership_size == 5  # genuinely stale

        # The stale member's old-config vote reaches `current`: known-stale
        # traffic, so `current` beacons instead of pulling.
        await current.handle_message(
            FastRoundPhase2bMessage(
                sender=stale.my_addr, configuration_id=old_config, endpoints=(victim,)
            )
        )
        assert current.metrics.counters["config_beacons_sent"] == 1
        # The beacon lands at `stale` (in-process broadcast is direct), whose
        # evidence pull brings it into the decided configuration.
        assert await wait_until(lambda: stale.membership_size == 4)
        assert stale.view.configuration_id == current.view.configuration_id
        assert stale.metrics.counters["config_catch_ups"] == 1
    finally:
        await current_server.shutdown()
        await stale_server.shutdown()
        await current.shutdown()
        await stale.shutdown()


@async_test
async def test_quiescent_cluster_traffic_is_bounded_to_the_idle_heartbeat():
    # The flip side of the liveness guarantees: a converged, healthy,
    # change-free cluster must generate NO redeliveries, NO beacons, NO
    # suspicion pulls — only the slow idle anti-entropy heartbeat — over an
    # hour of simulated time. Runaway background traffic would be a
    # liveness mechanism misfiring.
    network = InProcessNetwork()
    ids = [NodeId(0, i) for i in range(4)]
    eps = [ep(i) for i in range(4)]
    clock = ManualClock()
    settings = Settings()
    services, servers = [], []
    for i in range(4):
        service, server = build_service(
            network, i, eps, ids, settings=settings, clock=clock
        )
        await server.start()
        await service.start()
        services.append(service)
        servers.append(server)
    try:
        sim_hour_ms = 3_600_000
        step = 5_000
        for _ in range(sim_hour_ms // step):
            clock.advance_ms(step)
            for _ in range(20):
                await asyncio.sleep(0)
        expected_idle_pulls = sim_hour_ms // settings.config_sync_idle_interval_ms
        for service in services:
            c = service.metrics.counters
            assert c["alert_batches_redelivered"] == 0
            assert c["config_beacons_sent"] == 0
            assert c["kicked"] == 0
            # Only idle-heartbeat pulls, roughly one per idle interval (the
            # loop tick quantization allows a little slack, never runaway).
            assert c["config_catch_ups"] == 0  # same-config pulls adopt nothing
            snap = service.client.stats.snapshot()
            assert snap["msgs_tx"] <= expected_idle_pulls + 2, snap
            # ...and the heartbeat is genuinely alive, not silently dead.
            assert snap["msgs_tx"] >= expected_idle_pulls // 2, snap
    finally:
        for server in servers:
            await server.shutdown()
        for service in services:
            await service.shutdown()


@async_test
async def test_alert_redelivery_heals_a_lost_batch():
    # An observer's single alert-batch broadcast is lost toward one receiver
    # (dropped before reaching it); the redelivery loop re-broadcasts the
    # batch and the receiver's cut completes. Modeled at the service level:
    # the receiver simply misses the first batch, then gets the redelivery.
    network = InProcessNetwork()
    ids = [NodeId(0, i) for i in range(4)]
    eps = [ep(i) for i in range(4)]
    settings = Settings()
    settings.k, settings.h, settings.l = 4, 3, 2
    settings.alert_redelivery_interval_ms = 50
    sender_svc, sender_server = build_service(
        network, 0, eps, ids, settings=settings
    )
    await sender_server.start()
    await sender_svc.start()
    try:
        # Enqueue a DOWN alert; the batcher broadcasts it once; with nobody
        # at H yet and reports pending, the loop must re-broadcast.
        async with sender_svc._lock:
            sender_svc._edge_failure_notification(
                eps[3], sender_svc.view.configuration_id
            )
        assert await wait_until(
            lambda: sender_svc.metrics.counters["alert_batches_redelivered"] >= 2,
            timeout_s=10,
        )
    finally:
        await sender_server.shutdown()
        await sender_svc.shutdown()
