"""Tenant-fleet parity: B batched clusters must be bit-identical to B
independent ``VirtualCluster`` runs — the non-negotiable bar (the way
``tests/test_parallel_2d.py`` pinned the 2-D mesh).

The pinned differential grid stacks B=8 tenants compiled from FOUR distinct
sim scenario families (``partition_heal``, ``asymmetric_link``,
``crash_during_join``, ``churn_under_loss``) at two seeds each, with
per-tenant H/L/fd knob mixes, and drives the fleet against per-tenant
singles two ways:

- per STEP (``fleet_step``): the cut sequences, configuration ids, and
  decision rounds must match exactly, tenant by tenant;
- per WAVE (``fleet_wave`` — the lockstep multi-cut loop): every phase
  group's (rounds, cuts, config id, epoch, membership) must match the
  single-cluster ``run_until_membership`` exactly.

Plus the 3-D ``('tenant', 'cohort', 'nodes')`` mesh: rule-table shardings
with the leading tenant axis, mesh-step parity against the single-device
fleet, and the ShardingShapeError/pad_to_multiple discipline for a tenant
count that does not divide the tenant axis.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.protocol.fast_paxos import FastPaxos
from rapid_tpu.types import Endpoint
from rapid_tpu.utils.clock import ManualClock
from rapid_tpu.parallel.mesh import (
    COHORT_AXIS,
    NODE_AXIS,
    TENANT_AXIS,
    ShardingShapeError,
    fleet_state_shardings,
    make_mesh,
    pad_to_multiple,
    shard_fleet_faults,
    shard_fleet_state,
)
from rapid_tpu.sim.oracles import cuts_refine
from rapid_tpu.tenancy import TenantFleet, chaos
from rapid_tpu.tenancy.fleet import knob_shardings

#: The pinned grid: B=8 tenants over four distinct sim families x two seeds,
#: with a per-tenant knob mix (H/L/fd_threshold traced lanes — one compiled
#: fleet program serves every mix).
GRID_SPECS = [
    ("partition_heal", 1), ("partition_heal", 2),
    ("asymmetric_link", 1), ("asymmetric_link", 2),
    ("crash_during_join", 1), ("crash_during_join", 2),
    ("churn_under_loss", 1), ("churn_under_loss", 2),
]
GRID_KNOBS = [
    (9, 4, 1), (8, 3, 1), (7, 2, 1), (9, 4, 1),
    (8, 3, 1), (9, 4, 1), (7, 2, 1), (8, 3, 1),
]


def _drive_single(vc, max_steps):
    """(cuts, config_ids, decision_rounds) of a per-step single-cluster
    drive — the test_parallel_2d labeling ((slot, up/down) cut members)."""
    cuts, ids, rounds = [], [], []
    for i in range(max_steps):
        was_alive = np.asarray(vc.state.alive)
        events = vc.step()
        if bool(events.decided):
            mask = np.asarray(events.winner_mask)
            cuts.append(frozenset(
                (s, "down" if was_alive[s] else "up")
                for s in np.nonzero(mask)[0].tolist()
            ))
            ids.append(vc.config_id)
            rounds.append(i)
    return cuts, ids, rounds


def _injected_tenants(telemetry=False):
    """The grid's tenants with EVERY membership phase injected up front
    (maximum overlapped churn; both sides of the parity get the identical
    injections). ``telemetry=True`` carries the device telemetry plane —
    the drive itself must stay bit-identical either way."""
    scenarios = chaos.compile_fleet(
        GRID_SPECS, knobs=GRID_KNOBS, telemetry=telemetry
    )
    for scenario in scenarios:
        for group in scenario.groups:
            chaos._inject_group(scenario.vc, group)
    return scenarios


def test_grid_step_parity_bit_identical():
    singles = _injected_tenants()
    expected = [_drive_single(s.vc, 24) for s in singles]
    assert all(cuts for cuts, _, _ in expected), "grid produced no cuts"

    fleet_side = _injected_tenants()
    fleet = TenantFleet.from_clusters([s.vc for s in fleet_side])
    got_cuts = [[] for _ in fleet_side]
    got_ids = [[] for _ in fleet_side]
    got_rounds = [[] for _ in fleet_side]
    for i in range(24):
        was_alive = np.asarray(fleet.state.alive)
        events = fleet.step()
        decided = np.asarray(events.decided)
        if not decided.any():
            continue
        winners = np.asarray(events.winner_mask)
        ids_now = fleet.config_ids()
        for t in np.nonzero(decided)[0].tolist():
            got_cuts[t].append(frozenset(
                (s, "down" if was_alive[t, s] else "up")
                for s in np.nonzero(winners[t])[0].tolist()
            ))
            got_ids[t].append(ids_now[t])
            got_rounds[t].append(i)

    for t, (cuts, ids, rounds) in enumerate(expected):
        label = fleet_side[t].name
        assert got_rounds[t] == rounds, label
        assert got_ids[t] == ids, label
        assert got_cuts[t] == cuts, label
        # The sim battery's refinement relation as the comparator:
        # bit-identical sequences refine each other in both directions.
        assert cuts_refine(got_cuts[t], [[c] for c in cuts]) is None, label
        assert cuts_refine(cuts, [[c] for c in got_cuts[t]]) is None, label
    # Final states identical tenant by tenant.
    alive = np.asarray(fleet.state.alive)
    for t, scenario in enumerate(singles):
        np.testing.assert_array_equal(
            alive[t], np.asarray(scenario.vc.state.alive)
        )


@pytest.mark.slow
def test_grid_wave_parity_multi_phase():
    """The lockstep fleet wave, phase group by phase group, against the
    nested single-cluster multi-cut loop: (rounds, cuts, config id, epoch,
    membership) per phase and the final alive masks must match exactly —
    and the per-tenant oracle battery is clean on the genuine run.

    Rides the unfiltered check.sh pass (the PR-9 wave-parity precedent):
    tier-1's wall budget keeps the step-parity grid — the acceptance pin —
    and test_tenancy_chaos's genuine fleet run covers the wave path's
    phase-group resolution in-session."""
    fleet_result = chaos.run_fleet(
        chaos.compile_fleet(GRID_SPECS, knobs=GRID_KNOBS)
    )
    assert chaos.check_fleet(fleet_result) == []
    assert fleet_result.total_cuts >= len(GRID_SPECS)  # every tenant cut

    for t, (family, seed) in enumerate(GRID_SPECS):
        scenario = chaos.compile_tenant(family, seed, GRID_KNOBS[t])
        expected = scenario.schedule.n0
        for g, group in enumerate(scenario.groups):
            expected += chaos._inject_group(scenario.vc, group)
            rounds, cuts, resolved, _ = scenario.vc.run_until_membership(
                expected, max_steps=64, max_cuts=8, min_cuts=1,
            )
            record = fleet_result.phases[t][g]
            assert resolved and record.resolved, (scenario.name, g)
            assert record.cuts == cuts, (scenario.name, g)
            assert record.config_id == scenario.vc.config_id, (scenario.name, g)
            assert record.config_epoch == scenario.vc.config_epoch, (
                scenario.name, g,
            )
            assert record.members == scenario.vc.membership_size, (
                scenario.name, g,
            )
        assert fleet_result.final_slots[t] == frozenset(
            np.nonzero(np.asarray(scenario.vc.state.alive))[0].tolist()
        ), scenario.name


# ---------------------------------------------------------------------------
# Knob discipline
# ---------------------------------------------------------------------------


def test_fleet_rejects_mismatched_static_geometry():
    a = VirtualCluster.create(12, n_slots=16, k=4, h=3, l=1, cohorts=2,
                              fd_threshold=1, seed=0)
    b = VirtualCluster.create(12, n_slots=16, k=4, h=3, l=1, cohorts=4,
                              fd_threshold=1, seed=1)
    with pytest.raises(ValueError, match="fleet-static"):
        TenantFleet.from_clusters([a, b])
    # Knob fields may differ freely: same geometry, different H/L/fd.
    c = VirtualCluster.create(12, n_slots=16, k=4, h=2, l=1, cohorts=2,
                              fd_threshold=2, seed=2)
    fleet = TenantFleet.from_clusters([a, c])
    assert fleet.b == 2
    assert fleet.knobs.h.tolist() == [3, 2]
    assert fleet.knobs.fd_threshold.tolist() == [1, 2]


def test_fleet_rejects_invalid_watermarks():
    a = VirtualCluster.create(12, n_slots=16, k=4, h=5, l=1, cohorts=2,
                              fd_threshold=1, seed=0)
    with pytest.raises(ValueError, match="1 <= L <= H <= K"):
        TenantFleet.from_clusters([a])


# ---------------------------------------------------------------------------
# The ('tenant', 'cohort', 'nodes') mesh
# ---------------------------------------------------------------------------

MESH3D_SHAPE = (2, 2, 2)


def make_mesh_3d():
    return make_mesh(jax.devices()[:8], shape=MESH3D_SHAPE)


def _mesh_fleet(b=4, n_members=28, n_slots=32, cohorts=4):
    knobs = [(3, 1, 2), (4, 2, 2), (3, 1, 2), (4, 1, 2)][:b]
    fleet = TenantFleet.create(
        b, n_members, n_slots=n_slots, k=4, cohorts=cohorts, knobs=knobs,
        delivery_spread=1,
    )
    return fleet


def test_fleet_shardings_carry_leading_tenant_axis():
    mesh = make_mesh_3d()
    shardings = fleet_state_shardings(mesh)
    P = jax.sharding.PartitionSpec
    assert shardings.alive.spec == P(TENANT_AXIS, NODE_AXIS)
    assert shardings.report_bits.spec == P(TENANT_AXIS, COHORT_AXIS, NODE_AXIS)
    assert shardings.seen_down.spec == P(TENANT_AXIS, COHORT_AXIS)
    assert shardings.config_epoch.spec == P(TENANT_AXIS)
    assert knob_shardings(mesh).h.spec == P(TENANT_AXIS)
    # Placed leaves genuinely split over all eight devices: a [t, c, n]
    # leaf's per-device shard is 1/8 of global.
    fleet = _mesh_fleet()
    state = shard_fleet_state(fleet.state, mesh)
    for leaf in (state.report_bits, state.released, state.prop_mask):
        shard = leaf.addressable_shards[0].data
        assert shard.nbytes * 8 == leaf.nbytes, leaf.shape
    # [t] per-configuration lanes split over 'tenant' only.
    for leaf in (state.config_epoch, state.n_members):
        shard = leaf.addressable_shards[0].data
        assert shard.nbytes * 2 == leaf.nbytes, leaf.shape


def test_fleet_shard_names_indivisible_tenant_count():
    """Satellite: a tenant count that does not divide the 'tenant' mesh
    axis raises the named error with the pad_to_multiple fix — pad the
    fleet with idle tenants, never an opaque XLA failure."""
    mesh = make_mesh_3d()
    fleet = _mesh_fleet(b=3)
    with pytest.raises(ShardingShapeError) as err:
        shard_fleet_state(fleet.state, mesh)
    msg = str(err.value)
    assert "does not divide" in msg and "pad_to_multiple" in msg
    assert pad_to_multiple(3, MESH3D_SHAPE[0]) == 4
    padded = _mesh_fleet(b=pad_to_multiple(3, MESH3D_SHAPE[0]))
    shard_fleet_state(padded.state, mesh)


@pytest.mark.slow
def test_mesh_fleet_step_parity_against_single_device():
    """The audited fleet3d entrypoints (make_fleet_step/make_fleet_wave on
    the 3-D mesh) produce bit-identical per-tenant results to the
    single-device fleet — which the grid above ties to B independent
    clusters, closing the chain mesh -> fleet -> singles."""
    from rapid_tpu.tenancy.fleet import make_fleet_step, make_fleet_wave

    def crashed_fleet():
        fleet = _mesh_fleet()
        for t in range(fleet.b):
            # Per-tenant fault masks: different victims per tenant.
            crashed = fleet.faults.crashed.at[t, 1 + t].set(True)
            fleet.faults = fleet.faults._replace(crashed=crashed)
        return fleet

    single = crashed_fleet()
    for _ in range(10):
        single.step()
    single_ids = single.config_ids()

    mesh = make_mesh_3d()
    fleet = crashed_fleet()
    step = make_fleet_step(fleet.cfg, mesh)
    state = shard_fleet_state(fleet.state, mesh)
    faults = shard_fleet_faults(fleet.faults, mesh)
    knobs = jax.tree.map(
        lambda x, sh: jax.device_put(x, sh), fleet.knobs, knob_shardings(mesh)
    )
    for _ in range(10):
        state, events = step(state, faults, knobs)
    np.testing.assert_array_equal(
        np.asarray(state.alive), np.asarray(single.state.alive)
    )
    mesh_ids = [
        (int(hi) << 32) | int(lo)
        for hi, lo in zip(np.asarray(state.config_hi), np.asarray(state.config_lo))
    ]
    assert mesh_ids == single_ids

    # And the lockstep wave on the mesh: same multi-tenant resolution in
    # one dispatch.
    single2 = crashed_fleet()
    targets = single2.membership_sizes() - 1
    r1, c1, res1, sizes1 = single2.run_until_membership(
        targets, max_steps=32, max_cuts=4, min_cuts=1
    )
    assert res1.all()
    fleet2 = crashed_fleet()
    wave = make_fleet_wave(fleet2.cfg, mesh, max_cuts=4)
    state2, steps2, cuts2, resolved2, sizes2 = wave(
        shard_fleet_state(fleet2.state, mesh),
        shard_fleet_faults(fleet2.faults, mesh),
        knobs,
        jax.device_put(jnp.asarray(targets),
                       jax.sharding.NamedSharding(
                           mesh, jax.sharding.PartitionSpec(TENANT_AXIS))),
        jnp.int32(32),
        jax.device_put(jnp.ones(fleet2.b, jnp.int32),
                       jax.sharding.NamedSharding(
                           mesh, jax.sharding.PartitionSpec(TENANT_AXIS))),
    )
    assert np.asarray(resolved2).all()
    np.testing.assert_array_equal(np.asarray(steps2), r1)
    np.testing.assert_array_equal(np.asarray(cuts2), c1)
    np.testing.assert_array_equal(np.asarray(sizes2), sizes1)
    np.testing.assert_array_equal(
        np.asarray(state2.alive), np.asarray(single2.state.alive)
    )


# ---------------------------------------------------------------------------
# Decision-path telemetry: the per-tenant fast/classic lane split must speak
# the host protocol's vocabulary (FastPaxos.decided_path: "classic" iff the
# classic fallback's Paxos learner decided) and match B independent clusters
# counter-for-counter on the pinned differential grid.
# ---------------------------------------------------------------------------


def _host_committee_path(n, votes):
    """Drive a fully connected host FastPaxos committee (the test_paxos.py
    DirectNetwork shape: FIFO-pumped direct wiring) over the given per-node
    proposals; if the fast round stalls, one node's fallback fires a classic
    round. Returns the committee's unanimous ``decided_path`` label — the
    vocabulary the engine's decision-path lanes must reproduce."""

    def ep(i):
        return Endpoint("127.0.0.1", 47000 + i)

    instances = {}
    queue, pumping = [], []

    def pump(destination, request):
        queue.append((destination, request))
        if pumping:
            return
        pumping.append(True)
        try:
            while queue:
                dst, req = queue.pop(0)
                targets = (
                    [instances[dst]] if dst is not None
                    else list(instances.values())
                )
                for inst in targets:
                    inst.handle_message(req)
        finally:
            pumping.clear()

    decisions = {}
    clock = ManualClock()
    for i in range(n):
        addr = ep(i)
        instances[addr] = FastPaxos(
            my_addr=addr, configuration_id=1, membership_size=n,
            broadcast_fn=lambda req: pump(None, req),
            send_fn=pump,
            on_decide=lambda hosts, a=addr: decisions.setdefault(
                a, tuple(hosts)
            ),
            clock=clock, rng=random.Random(i),
        )
    for i, proposal in enumerate(votes):
        instances[ep(i)].propose(proposal, recovery_delay_ms=1e9)
    if not decisions:
        instances[ep(0)].start_classic_paxos_round()
    assert len(decisions) == n and len(set(decisions.values())) == 1
    paths = {inst.decided_path for inst in instances.values()}
    assert len(paths) == 1
    return paths.pop()


def test_decision_path_lanes_speak_the_host_fast_paxos_vocabulary():
    """Matched host/engine contention shapes land on the same path label.

    Host side: a unanimous committee decides with ``decided_path == "fast"``;
    a split committee (no fast quorum) decides through the fallback with
    ``decided_path == "classic"`` (fast_paxos.py: "classic" iff the inner
    Paxos decided). Engine side: the same two contention shapes must place
    their decision in the matching telemetry lane — the round body's
    ``fb_decided`` is gated on ``~fast_decided`` (fallback_due), so the lanes
    are mutually exclusive exactly like the host label."""

    def ep(i):
        return Endpoint("127.0.0.1", 47000 + i)

    # Host labels for the two shapes.
    unanimous = [(ep(9999),)] * 10
    assert _host_committee_path(10, unanimous) == "fast"
    split = [(ep(9999),)] * 7 + [(ep(8888),)] * 3  # quorum(10)=8: stalls
    assert _host_committee_path(10, split) == "classic"

    # Engine, unanimous shape: one crash every cohort agrees on.
    vc = VirtualCluster.create(16, fd_threshold=2, seed=3, telemetry=True)
    vc.crash([5])
    rounds, events = vc.run_until_converged(max_steps=32)
    assert events is not None and bool(events.fast_decided)
    vc.sync()
    activity = vc.activity
    assert activity["decisions_fast"] == 1
    assert activity["decisions_classic"] == 0
    assert activity["fast_path_share"] == 1.0

    # Engine, split shape (the test_engine.py contested-round scenario with
    # the telemetry plane on): cohort 1 never hears the second victim's
    # observers, so its subset proposal denies the fast round its quorum and
    # the classic fallback decides the plurality cut.
    n = 120
    vc = VirtualCluster.create(n, fd_threshold=2, seed=11, telemetry=True)
    cohort_of = np.zeros(n, dtype=np.int32)
    cohort_of[80:] = 1
    vc.assign_cohorts(cohort_of)
    v1, v2 = 10, 60
    vc.crash([v1, v2])
    rx = np.zeros((vc.cfg.c, vc.cfg.n), dtype=bool)
    rx[1, np.asarray(vc.state.obs_idx)[:, v2]] = True
    vc.set_rx_block(rx)
    rounds, events = vc.run_until_converged(max_steps=64)
    assert events is not None and not bool(events.fast_decided)
    vc.sync()
    activity = vc.activity
    assert activity["decisions_classic"] == 1
    assert activity["decisions_fast"] == 0
    assert activity["fast_path_share"] == 0.0
    # Every announced-but-undecided round before the fallback landed is a
    # conflict round; the fallback timer alone guarantees several.
    assert activity["conflict_rounds"] >= vc.cfg.fallback_rounds


def test_grid_decision_path_split_fleet_matches_singles():
    """Per-tenant fast/classic counters on the differential grid: the fleet's
    ``tenant_activity`` must match (a) the host-vocabulary labels recorded
    from each single's per-decision ``events.fast_decided`` and (b) the
    single's own fetched lanes, digest field by digest field."""
    singles = _injected_tenants(telemetry=True)
    expected = []
    for scenario in singles:
        fast = classic = 0
        for _ in range(24):
            events = scenario.vc.step()
            if bool(events.decided):
                # The host label ("classic" iff the classic fallback
                # decided); the engine's paths are mutually exclusive.
                if bool(events.fast_decided):
                    fast += 1
                else:
                    classic += 1
        scenario.vc.sync()
        activity = scenario.vc.activity
        assert activity["decisions_fast"] == fast, scenario.name
        assert activity["decisions_classic"] == classic, scenario.name
        expected.append((fast, classic, activity))
    assert sum(f + c for f, c, _ in expected), "grid produced no decisions"

    fleet_side = _injected_tenants(telemetry=True)
    fleet = TenantFleet.from_clusters([s.vc for s in fleet_side])
    for _ in range(24):
        fleet.step()
    fleet.sync()
    tenant_activity = fleet.tenant_activity
    digest_fields = tuple(expected[0][2])
    for t, (fast, classic, single_activity) in enumerate(expected):
        label = fleet_side[t].name
        got = tenant_activity[t]
        assert got["decisions_fast"] == fast, label
        assert got["decisions_classic"] == classic, label
        for field in digest_fields:
            assert got[field] == single_activity[field], (label, field)
    # The pooled aggregate recomputes the share over the summed split.
    pooled = fleet.activity
    total_fast = sum(f for f, _, _ in expected)
    total = sum(f + c for f, c, _ in expected)
    assert pooled["decisions_fast"] == total_fast
    assert pooled["fast_path_share"] == pytest.approx(total_fast / total)
