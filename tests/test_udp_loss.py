"""Quantifying the hybrid transport's datagram-loss tradeoff.

Datagram loss costs the hybrid TCP+UDP transport convergence LATENCY, never
liveness: the protocol's delivery-liveness mechanisms (settings.py; pinned
individually in tests/test_delivery_liveness.py) re-broadcast unresolved
alert batches, re-offer undecided fast-round votes, escalate classic rounds
until a decision lands, and let a node that missed a decision pull the
configuration from a peer over the reliable TCP path. Even a decision
naming a joiner whose every UP alert datagram was lost — probability ~p^O
at loss rate p with O distinct observers — resolves by config pull rather
than a forced rejoin. These tests pin that envelope end-to-end under
seeded loss: churn converges, nobody rejoins, nobody is kicked.

The full latency curve is measured by examples/udp_loss_curve.py; its
committed results live in EVALUATION.md.
"""

import asyncio
import functools
import random

import pytest

from rapid_tpu.messaging.udp import LossyDatagramClient, UdpHybridServer
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint

from helpers import free_endpoints, wait_until


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=120)

        asyncio.run(with_timeout())

    return wrapper


def fast_settings() -> Settings:
    s = Settings()
    s.batching_window_ms = 20
    s.failure_detector_interval_ms = 50
    s.rpc_timeout_ms = 500
    s.rpc_join_timeout_ms = 2000
    s.rpc_probe_timeout_ms = 200
    s.consensus_fallback_base_delay_ms = 1000
    return s


async def run_lossy_churn(loss_rate: float, seed: int):
    """5-node bring-up -> 3-node join wave -> 1 crash, every datagram lane
    subject to seeded loss. Returns (clusters, forced_rejoins, kicked)."""
    settings = fast_settings()
    fd = StaticFailureDetectorFactory()
    rng = random.Random(seed)
    eps = free_endpoints(8)

    def ep(i: int) -> Endpoint:
        return eps[i]

    def client(i: int) -> LossyDatagramClient:
        return LossyDatagramClient(
            ep(i), settings, loss_rate=loss_rate,
            rng=random.Random(rng.randrange(1 << 30)),
        )

    clusters = [
        await Cluster.start(ep(0), settings=settings, client=client(0),
                            server=UdpHybridServer(ep(0)), fd_factory=fd,
                            rng=random.Random(seed))
    ]
    for i in range(1, 5):
        clusters.append(
            await Cluster.join(ep(0), ep(i), settings=settings, client=client(i),
                               server=UdpHybridServer(ep(i)), fd_factory=fd,
                               rng=random.Random(seed + i))
        )
    assert await wait_until(lambda: all(c.membership_size == 5 for c in clusters))

    # Concurrent join wave: UP alerts and votes ride lossy datagrams.
    joiners = await asyncio.gather(*(
        Cluster.join(ep(0), ep(i), settings=settings, client=client(i),
                     server=UdpHybridServer(ep(i)), fd_factory=fd,
                     rng=random.Random(seed + i))
        for i in range(5, 8)
    ))
    clusters.extend(joiners)
    assert await wait_until(
        lambda: all(c.membership_size == 8 for c in clusters), timeout_s=60
    )

    # Crash: DOWN alerts ride lossy datagrams too.
    victim = clusters[3]
    await victim.shutdown()
    fd.add_failed_nodes([victim.listen_address])
    survivors = [c for c in clusters if c is not victim]
    assert await wait_until(
        lambda: all(c.membership_size == 7 for c in survivors), timeout_s=60
    )

    forced_rejoins = sum(
        c.service.metrics.counters["decision_missing_joiner_uuid"] for c in survivors
    )
    kicked = sum(c.service.metrics.counters["kicked"] for c in survivors)
    return survivors, forced_rejoins, kicked


@async_test
async def test_no_forced_rejoin_at_10pct_loss():
    # The pin: with the default alert fan-out (every distinct observer of a
    # joiner broadcasts its own UP batch) and timer-based batch redelivery,
    # 10% datagram loss never forces a rejoin — the loss envelope for missing
    # a UUID entirely is ~0.1^(observers × redeliveries), and even that case
    # would resolve by config pull. Convergence still completes.
    survivors, forced_rejoins, kicked = await run_lossy_churn(loss_rate=0.10, seed=42)
    try:
        assert forced_rejoins == 0
        assert kicked == 0
        assert len({tuple(c.membership) for c in survivors}) == 1
    finally:
        await asyncio.gather(*(c.shutdown() for c in survivors), return_exceptions=True)


@pytest.mark.slow
@async_test
async def test_converges_under_heavy_loss():
    # Rides the unfiltered check.sh pass (~18 s wall of seeded-loss churn);
    # the 10%-loss no-forced-rejoin test keeps the loss envelope in tier-1.
    # 30% loss: convergence must still complete — lost votes are re-offered
    # and classic rounds escalate on every fallback tick, lost alert batches
    # are re-broadcast on the redelivery timer, and any node that misses the
    # decision itself catches up by config pull. No zero-rejoin guarantee is
    # claimed at this rate.
    survivors, forced_rejoins, _ = await run_lossy_churn(loss_rate=0.30, seed=7)
    try:
        assert len({tuple(c.membership) for c in survivors}) == 1
        assert all(c.membership_size == 7 for c in survivors)
    finally:
        await asyncio.gather(*(c.shutdown() for c in survivors), return_exceptions=True)


@async_test
async def test_loss_actually_drops_datagrams():
    # The instrument itself: loss strikes AFTER the sender commits to the
    # datagram path — no TCP fallback engages (a fallback would defeat the
    # injection). The joiner at 100% loss still joins (joins ride TCP), but
    # its leave broadcast is eaten and counted; the clean seed drops nothing.
    settings = fast_settings()
    a, b = free_endpoints(2)
    fd = StaticFailureDetectorFactory()
    clean = LossyDatagramClient(a, settings, loss_rate=0.0, rng=random.Random(1))
    lossy = LossyDatagramClient(b, settings, loss_rate=1.0, rng=random.Random(2))
    c0 = await Cluster.start(a, settings=settings, client=clean,
                             server=UdpHybridServer(a), fd_factory=fd,
                             rng=random.Random(0))
    c1 = await Cluster.join(a, b, settings=settings, client=lossy,
                            server=UdpHybridServer(b), fd_factory=fd,
                            rng=random.Random(1))
    try:
        assert await wait_until(lambda: c0.membership_size == 2 and c1.membership_size == 2)
        # Force one-way traffic through the lossy client: a leave broadcast.
        await c1.leave_gracefully()
        assert clean.datagrams_dropped == 0
        assert lossy.datagrams_dropped > 0
        # The seed never heard the leave: the datagram genuinely vanished.
        assert c0.membership_size == 2
    finally:
        await asyncio.gather(c0.shutdown(), c1.shutdown(), return_exceptions=True)
