"""Classic-Paxos tests: coordinator value-pick rule tables and full-protocol
runs over direct wiring with selective message drops, mirroring the reference's
PaxosTests (rapid/src/test/java/com/vrg/rapid/PaxosTests.java)."""

import random
from typing import Dict, List, Optional, Tuple, Type

import pytest

from rapid_tpu.protocol.fast_paxos import FastPaxos, fast_paxos_quorum
from rapid_tpu.protocol.paxos import select_proposal_using_coordinator_rule
from rapid_tpu.types import (
    Endpoint,
    FastRoundPhase2bMessage,
    Phase1bMessage,
    Rank,
)
from rapid_tpu.utils.clock import ManualClock


def ep(i: int) -> Endpoint:
    return Endpoint("127.0.0.1", i)


def p1b(sender_port: int, rnd: Rank, vrnd: Rank, vval: Tuple[Endpoint, ...]) -> Phase1bMessage:
    return Phase1bMessage(
        sender=ep(sender_port), configuration_id=1, rnd=rnd, vrnd=vrnd, vval=vval
    )


CRND = Rank(2, 1)
V1 = (ep(1001),)
V2 = (ep(1002),)
V3 = (ep(1003),)


class TestCoordinatorRule:
    def test_empty_messages_raise(self):
        with pytest.raises(ValueError):
            select_proposal_using_coordinatorrule_alias = select_proposal_using_coordinator_rule(
                [], 5
            )

    def test_all_empty_vvals_choose_nothing(self):
        msgs = [p1b(i, CRND, Rank(0, 0), ()) for i in range(3)]
        assert select_proposal_using_coordinator_rule(msgs, 5) == ()

    def test_single_voter_value_wins(self):
        msgs = [p1b(0, CRND, Rank(1, 1), V1)] + [p1b(i, CRND, Rank(0, 0), ()) for i in range(1, 4)]
        assert select_proposal_using_coordinator_rule(msgs, 5) == V1

    def test_unique_value_at_max_vrnd_wins(self):
        msgs = [
            p1b(0, CRND, Rank(1, 1), V1),
            p1b(1, CRND, Rank(1, 1), V1),
            p1b(2, CRND, Rank(0, 0), ()),
        ]
        assert select_proposal_using_coordinator_rule(msgs, 5) == V1

    def test_lower_vrnd_values_are_ignored(self):
        msgs = [
            p1b(0, CRND, Rank(1, 2), V2),
            p1b(1, CRND, Rank(1, 1), V1),
            p1b(2, CRND, Rank(1, 1), V1),
        ]
        assert select_proposal_using_coordinator_rule(msgs, 5) == V2

    def test_majority_over_quarter_wins(self):
        # N=10: need count > N/4 = 2.5, i.e. >= 3 among max-vrnd votes.
        msgs = (
            [p1b(i, CRND, Rank(1, 1), V1) for i in range(3)]
            + [p1b(3 + i, CRND, Rank(1, 1), V2) for i in range(2)]
            + [p1b(5 + i, CRND, Rank(1, 1), V3) for i in range(1)]
        )
        assert select_proposal_using_coordinator_rule(msgs, 10) == V1

    def test_no_quarter_majority_picks_any_nonempty(self):
        # N=20: threshold > 5; two values with 2 votes each — any proposed
        # value is safe.
        msgs = [
            p1b(0, CRND, Rank(1, 1), V1),
            p1b(1, CRND, Rank(1, 1), V1),
            p1b(2, CRND, Rank(1, 1), V2),
            p1b(3, CRND, Rank(1, 1), V2),
        ]
        chosen = select_proposal_using_coordinator_rule(msgs, 20)
        assert chosen in (V1, V2)

    def test_shuffled_quorums_always_pick_safe_value(self):
        # Mirrors the reference's shuffled-iteration scheme: whenever one value
        # has a fast-round quorum intersection (> N/4 identical at max vrnd),
        # every shuffle must pick it.
        rng = random.Random(42)
        n = 10
        msgs = [p1b(i, CRND, Rank(1, 1), V1) for i in range(4)] + [
            p1b(4 + i, CRND, Rank(1, 1), V2) for i in range(2)
        ]
        for _ in range(100):
            rng.shuffle(msgs)
            assert select_proposal_using_coordinator_rule(msgs, n) == V1


# ---------------------------------------------------------------------------
# Full-protocol runs over direct wiring (reference: PaxosTests.java:72-191,
# DirectMessagingClient/DirectBroadcaster :424-476).
# ---------------------------------------------------------------------------


class DirectNetwork:
    """Synchronously delivers consensus messages between FastPaxos instances,
    with optional per-message-type drops (PaxosTests.java:424-446)."""

    def __init__(self) -> None:
        self.instances: Dict[Endpoint, FastPaxos] = {}
        self.drop_types: List[Type] = []
        self._queue: List[Tuple[Optional[Endpoint], object]] = []
        self._pumping = False

    def broadcast(self, request) -> None:
        self._enqueue(None, request)

    def send(self, destination: Endpoint, request) -> None:
        self._enqueue(destination, request)

    def _enqueue(self, destination, request) -> None:
        if any(isinstance(request, t) for t in self.drop_types):
            return
        self._queue.append((destination, request))
        # Pump iteratively (not recursively) so delivery order is FIFO like a
        # real network, and deep chains don't blow the stack.
        if self._pumping:
            return
        self._pumping = True
        try:
            while self._queue:
                dst, req = self._queue.pop(0)
                targets = [self.instances[dst]] if dst is not None else list(
                    self.instances.values()
                )
                for instance in targets:
                    instance.handle_message(req)
        finally:
            self._pumping = False


def build_cluster(n: int, network: DirectNetwork, decisions: Dict[Endpoint, Tuple[Endpoint, ...]]):
    clock = ManualClock()
    for i in range(n):
        addr = ep(i)

        def on_decide(hosts, addr=addr):
            assert addr not in decisions, "node decided twice"
            decisions[addr] = tuple(hosts)

        network.instances[addr] = FastPaxos(
            my_addr=addr,
            configuration_id=1,
            membership_size=n,
            broadcast_fn=network.broadcast,
            send_fn=network.send,
            on_decide=on_decide,
            clock=clock,
            rng=random.Random(i),
        )
    return clock


@pytest.mark.parametrize("n", [5, 6, 10, 11, 20])
def test_all_agree_fast_round(n):
    network = DirectNetwork()
    decisions: Dict[Endpoint, Tuple[Endpoint, ...]] = {}
    build_cluster(n, network, decisions)
    proposal = (ep(9999),)
    for instance in list(network.instances.values()):
        instance.propose(proposal, recovery_delay_ms=1e9)
    assert len(decisions) == n
    assert all(d == proposal for d in decisions.values())


@pytest.mark.parametrize("n", [6, 10, 20])
def test_fast_round_silenced_classic_recovers(n):
    network = DirectNetwork()
    decisions: Dict[Endpoint, Tuple[Endpoint, ...]] = {}
    build_cluster(n, network, decisions)
    network.drop_types = [FastRoundPhase2bMessage]
    proposal = (ep(9999),)
    for instance in list(network.instances.values()):
        instance.propose(proposal, recovery_delay_ms=1e9)
    assert decisions == {}
    # One node's fallback timer fires and drives a classic round.
    network.drop_types = []
    network.instances[ep(0)].start_classic_paxos_round()
    assert len(decisions) == n
    assert all(d == proposal for d in decisions.values())


@pytest.mark.parametrize("n,votes_a", [(6, 4), (10, 7), (20, 14)])
def test_mixed_fast_round_then_classic(n, votes_a):
    """Fast round with conflicting proposals is silenced; a classic round must
    still decide on one of the proposed values, everywhere."""
    network = DirectNetwork()
    decisions: Dict[Endpoint, Tuple[Endpoint, ...]] = {}
    build_cluster(n, network, decisions)
    network.drop_types = [FastRoundPhase2bMessage]
    va, vb = (ep(9999),), (ep(8888),)
    for i, instance in enumerate(network.instances.values()):
        instance.propose(va if i < votes_a else vb, recovery_delay_ms=1e9)
    network.drop_types = []
    network.instances[ep(1)].start_classic_paxos_round()
    assert len(decisions) == n
    chosen = set(decisions.values())
    assert len(chosen) == 1
    assert chosen.pop() in (va, vb)


def test_competing_coordinators_highest_rank_wins():
    n = 10
    network = DirectNetwork()
    decisions: Dict[Endpoint, Tuple[Endpoint, ...]] = {}
    build_cluster(n, network, decisions)
    network.drop_types = [FastRoundPhase2bMessage]
    proposal = (ep(9999),)
    for instance in list(network.instances.values()):
        instance.propose(proposal, recovery_delay_ms=1e9)
    network.drop_types = []
    # Two nodes race to coordinate round 2; ranks order them.
    network.instances[ep(0)].start_classic_paxos_round()
    network.instances[ep(1)].start_classic_paxos_round()
    assert len(decisions) == n
    assert all(d == proposal for d in decisions.values())


# ---------------------------------------------------------------------------
# Fast-round quorum tables (reference: FastPaxosWithoutFallbackTests.java).
# ---------------------------------------------------------------------------


def feed_votes(instance: FastPaxos, proposal, senders) -> None:
    for s in senders:
        instance.handle_message(
            FastRoundPhase2bMessage(sender=s, configuration_id=1, endpoints=proposal)
        )


@pytest.mark.parametrize("n", [5, 6, 10, 11, 20, 21, 102])
def test_fast_quorum_exact_threshold(n):
    quorum = fast_paxos_quorum(n)
    decided: List[Tuple[Endpoint, ...]] = []
    instance = FastPaxos(
        my_addr=ep(0),
        configuration_id=1,
        membership_size=n,
        broadcast_fn=lambda req: None,
        send_fn=lambda dst, req: None,
        on_decide=lambda hosts: decided.append(tuple(hosts)),
        clock=ManualClock(),
    )
    proposal = (ep(9999),)
    feed_votes(instance, proposal, [ep(100 + i) for i in range(quorum - 1)])
    assert decided == []
    feed_votes(instance, proposal, [ep(100 + quorum - 1)])
    assert decided == [proposal]


@pytest.mark.parametrize("n", [6, 10, 20, 48, 102])
def test_fast_quorum_conflicts_beyond_f_block_decision(n):
    quorum = fast_paxos_quorum(n)
    f = n - quorum
    decided: List[Tuple[Endpoint, ...]] = []
    instance = FastPaxos(
        my_addr=ep(0),
        configuration_id=1,
        membership_size=n,
        broadcast_fn=lambda req: None,
        send_fn=lambda dst, req: None,
        on_decide=lambda hosts: decided.append(tuple(hosts)),
        clock=ManualClock(),
    )
    va, vb = (ep(9999),), (ep(8888),)
    # f + 1 conflicting votes leave fewer than quorum identical votes possible.
    feed_votes(instance, vb, [ep(100 + i) for i in range(f + 1)])
    feed_votes(instance, va, [ep(200 + i) for i in range(n - f - 1)])
    assert decided == []


def test_duplicate_and_stale_votes_ignored():
    n = 6
    decided: List[Tuple[Endpoint, ...]] = []
    instance = FastPaxos(
        my_addr=ep(0),
        configuration_id=1,
        membership_size=n,
        broadcast_fn=lambda req: None,
        send_fn=lambda dst, req: None,
        on_decide=lambda hosts: decided.append(tuple(hosts)),
        clock=ManualClock(),
    )
    proposal = (ep(9999),)
    # Duplicate senders only count once; wrong config ids are discarded.
    for _ in range(10):
        feed_votes(instance, proposal, [ep(101)])
    instance.handle_message(
        FastRoundPhase2bMessage(sender=ep(102), configuration_id=999, endpoints=proposal)
    )
    assert decided == []


# ---------------------------------------------------------------------------
# Coordinator-rule case tables (reference: PaxosTests.java:195-397): the full
# (N, vote-distribution) families, each against 100 shuffled quorums.
# ---------------------------------------------------------------------------

P1 = (ep(5891), ep(5821))
P2 = (ep(5821), ep(5872))
NOISE = (ep(1), ep(2))
_PN = (P1, P2, NOISE)
_PN_SWAP = (P2, P1, NOISE)
_INT_MAX = 2**31 - 1


# (n, p1n, p2n, proposals, valid proposal indices) — PaxosTests.java:256-303.
# p1n messages carry proposals[0] at rank (1, 1); p2n messages carry
# proposals[1] at rank (0, INT_MAX); the rest carry the noise proposal at
# rank (0, i).
DIFFERENT_RANK_CASES = [
    # Fast Paxos quorum of highest-ranked proposal (p1n + p2n == N).
    (6, 4, 2, _PN, {0}),
    (6, 5, 1, _PN, {0}),
    (6, 6, 0, _PN, {0}),
    (9, 6, 3, _PN, {0, 1}),
    (9, 7, 2, _PN, {0}),
    (9, 8, 1, _PN, {0}),
    # One / two votes of highest rank: may or may not be picked.
    (6, 1, 5, _PN, {0, 1}),
    (6, 2, 4, _PN, {0, 1}),
    # intersection(R, Q) of highest rank.
    (6, 3, 3, _PN, {0}),
    (6, 3, 3, _PN_SWAP, {0}),
    # p1n + p2n < N.
    (6, 4, 1, _PN, {0}),
    (6, 5, 1, _PN, {0}),
    (9, 6, 1, _PN, {0, 1, 2}),
    (9, 7, 1, _PN, {0}),
    (9, 8, 1, _PN, {0}),
    (6, 1, 2, _PN, {0, 1, 2}),
    (6, 2, 1, _PN, {0, 1, 2}),
    (6, 3, 0, _PN, {0}),
    (6, 3, 0, _PN_SWAP, {0}),
]

# Same-rank table (PaxosTests.java:305-397): p1n AND p2n messages both carry
# rank (1, 1); the rest carry the noise proposal at rank (0, i).
SAME_RANK_CASES = [
    (6, 4, 2, _PN, {0, 1}),
    (6, 5, 1, _PN, {0}),
    (6, 6, 0, _PN, {0}),
    (9, 6, 3, _PN, {0, 1}),
    (9, 7, 2, _PN, {0}),
    (9, 8, 1, _PN, {0}),
    (6, 3, 3, _PN, {0, 1}),
    (6, 3, 3, _PN_SWAP, {0, 1}),
    (6, 4, 1, _PN, {0, 1}),
    (6, 5, 0, _PN, {0}),
    (9, 6, 1, _PN, {0, 1, 2}),
    (9, 7, 1, _PN, {0}),
    (9, 8, 1, _PN, {0}),
    (6, 1, 2, _PN, {0, 1, 2}),
    (6, 2, 1, _PN, {0, 1, 2}),
    (6, 3, 0, _PN, {0}),
    (6, 3, 0, _PN_SWAP, {0}),
]


def _run_rule_table_case(n, p1n, p2n, proposals, valid, same_rank: bool):
    valid_values = {proposals[i] for i in valid}
    rank1 = Rank(1, 1)
    rank2 = rank1 if same_rank else Rank(0, _INT_MAX)
    rng = random.Random((n, p1n, p2n, same_rank).__hash__())
    for _ in range(100):
        msgs = []
        for i in range(p1n):
            msgs.append(p1b(i, CRND, rank1, proposals[0]))
        for i in range(p2n):
            msgs.append(p1b(p1n + i, CRND, rank2, proposals[1]))
        for i in range(p1n + p2n, n):
            msgs.append(p1b(i, CRND, Rank(0, i), proposals[2]))
        rng.shuffle(msgs)
        quorum = msgs[: n // 2 + 1]
        chosen = select_proposal_using_coordinator_rule(quorum, n)
        assert chosen in valid_values, (
            f"chose {chosen} outside valid set for case "
            f"(n={n}, p1n={p1n}, p2n={p2n}, same_rank={same_rank})"
        )


@pytest.mark.parametrize("n,p1n,p2n,proposals,valid", DIFFERENT_RANK_CASES)
def test_coordinator_rule_table_different_ranks(n, p1n, p2n, proposals, valid):
    _run_rule_table_case(n, p1n, p2n, proposals, valid, same_rank=False)


@pytest.mark.parametrize("n,p1n,p2n,proposals,valid", SAME_RANK_CASES)
def test_coordinator_rule_table_same_rank(n, p1n, p2n, proposals, valid):
    _run_rule_table_case(n, p1n, p2n, proposals, valid, same_rank=True)


# Classic-round-after-silenced-fast-round table
# (PaxosTests.java:141-191's testClassicRoundAfterSuccessfulFastRoundMixedValues):
# proposal-1 gets N - p2votes of the fast votes, all fast-round phase2b
# messages are dropped, then one node drives a classic round. When one
# proposal held a fast quorum of the (never-delivered) votes, the classic
# round MUST relearn exactly it; otherwise any proposed value may win.
CLASSIC_AFTER_MIXED_CASES = [
    (6, 5, "p2"),
    (6, 1, "p1"),
    (6, 4, "any"),
    (6, 2, "any"),
    (5, 4, "p2"),
    (5, 1, "p1"),
    (10, 4, "any"),
    (10, 1, "any"),
]


@pytest.mark.parametrize("n,p2votes,expected", CLASSIC_AFTER_MIXED_CASES)
def test_classic_round_after_mixed_fast_round_table(n, p2votes, expected):
    network = DirectNetwork()
    decisions: Dict[Endpoint, Tuple[Endpoint, ...]] = {}
    build_cluster(n, network, decisions)
    network.drop_types = [FastRoundPhase2bMessage]
    va, vb = (ep(9999),), (ep(8888),)
    for i, instance in enumerate(network.instances.values()):
        instance.propose(va if i < n - p2votes else vb, recovery_delay_ms=1e9)
    assert decisions == {}
    network.drop_types = []
    network.instances[ep(0)].start_classic_paxos_round()
    assert len(decisions) == n
    chosen = set(decisions.values())
    assert len(chosen) == 1
    winner = chosen.pop()
    if expected == "p1":
        assert winner == va
    elif expected == "p2":
        assert winner == vb
    else:
        assert winner in (va, vb)
