"""Observability subsystem tests: flight recorder, trace-context propagation,
Prometheus/JSON exposition, and the traceview timeline merger.

The acceptance surface of the observability layer is pinned here: the
recorder's ring semantics under simulated time, the trace id's optional
wire encoding (byte-identical frames when absent — the golden proto fixtures
in tests/test_wire_fixtures.py stay valid), the stable Prometheus metric
names (a golden list: renaming a metric is an API break for every scrape
config), and the end-to-end claim — a 3-node in-process cluster's
crash-and-converge run merges into one causally-ordered timeline
(alert → proposal → decision → delivery on every surviving node) that
renders as valid Chrome trace-event JSON.
"""

import dataclasses
import json
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import clustertop  # noqa: E402  — tools/clustertop.py, the live dashboard
import traceview  # noqa: E402  — tools/traceview.py, the timeline merger

from rapid_tpu.messaging.codec import decode_request, encode_request  # noqa: E402
from rapid_tpu.messaging.inprocess import InProcessNetwork  # noqa: E402
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory  # noqa: E402
from rapid_tpu.types import (  # noqa: E402
    AlertMessage,
    BatchedAlertMessage,
    EdgeStatus,
    Endpoint,
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    Rank,
)
from rapid_tpu.utils import exposition  # noqa: E402
from rapid_tpu.utils.clock import ManualClock  # noqa: E402
from rapid_tpu.utils.flight_recorder import (  # noqa: E402
    EventName,
    FlightRecorder,
    mint_trace_id,
)
from rapid_tpu.utils.health import NodeHealth, aggregate_health  # noqa: E402
from rapid_tpu.utils.histogram import LogHistogram  # noqa: E402

from tests.test_cluster import (  # noqa: E402
    all_converged,
    async_test,
    ep,
    start_cluster,
    shutdown_all,
)
from tests.test_wire_fixtures import canonical_requests  # noqa: E402
from tests.helpers import wait_until  # noqa: E402


# ---------------------------------------------------------------------------
# flight recorder: ring semantics under simulated time
# ---------------------------------------------------------------------------


def test_ring_buffer_wraparound():
    clock = ManualClock()
    rec = FlightRecorder(node="n1", clock=clock, capacity=4)
    for i in range(10):
        rec.record(EventName.ALERT_ENQUEUED, config_id=i)
    assert len(rec) == 4
    assert rec.recorded_total == 10
    assert rec.dropped == 6
    # Oldest-first, and only the last `capacity` events survive.
    assert [e.seq for e in rec.events()] == [6, 7, 8, 9]
    assert [e.fields for e in rec.tail(2)] == [{}, {}]
    assert [e.config_id for e in rec.tail(2)] == [8, 9]


def test_ring_buffer_below_capacity():
    rec = FlightRecorder(node="n1", clock=ManualClock(), capacity=8)
    rec.record(EventName.VIEW_CHANGE, config_id=1)
    rec.record(EventName.KICKED, config_id=1)
    assert len(rec) == 2 and rec.dropped == 0
    assert [e.name for e in rec.events()] == [EventName.VIEW_CHANGE, EventName.KICKED]


def test_recorder_uses_simulated_clock():
    clock = ManualClock()
    rec = FlightRecorder(node="n1", clock=clock, capacity=8)
    rec.record(EventName.ALERT_ENQUEUED)
    clock.advance_ms(250.0)
    rec.record(EventName.FAST_ROUND_PROPOSAL)
    clock.advance_ms(4750.0)
    rec.record(EventName.CONSENSUS_DECIDED)
    assert [e.t_ms for e in rec.events()] == [0.0, 250.0, 5000.0]


def test_snapshot_tail_and_shape():
    rec = FlightRecorder(node="n9", clock=ManualClock(), capacity=16)
    for i in range(5):
        rec.record(EventName.ALERT_BATCH_RX, config_id=7, trace_id=0xAB, alerts=i)
    snap = rec.snapshot(tail=2)
    assert snap["node"] == "n9"
    assert snap["capacity"] == 16
    assert snap["recorded_total"] == 5 and snap["dropped"] == 0
    assert [e["fields"]["alerts"] for e in snap["events"]] == [3, 4]
    assert snap["events"][0]["name"] == "alert_batch_rx"
    # The snapshot is the JSON artifact --metrics-dump writes: it must be
    # serializable as-is.
    json.dumps(snap)


def test_mint_trace_id_deterministic_and_nonzero():
    a = mint_trace_id("10.0.0.1:9001", 42, 1000.0)
    assert a == mint_trace_id("10.0.0.1:9001", 42, 1000.0)
    assert a != mint_trace_id("10.0.0.2:9001", 42, 1000.0)
    assert a != mint_trace_id("10.0.0.1:9001", 43, 1000.0)
    assert 0 < a < 2**64


def test_every_event_name_has_a_phase_rank():
    # traceview's tie-breaking is total over the registered vocabulary: a
    # new EventName member without a rank would KeyError at merge time.
    for name in EventName:
        assert isinstance(name.phase_rank, int)


# ---------------------------------------------------------------------------
# trace-context wire encoding: optional, trailing, byte-identical when absent
# ---------------------------------------------------------------------------

_EP1 = Endpoint("10.0.0.1", 5000)
_EP2 = Endpoint("10.0.0.2", 5001)
_ALERT = AlertMessage(
    edge_src=_EP1, edge_dst=_EP2, edge_status=EdgeStatus.DOWN,
    configuration_id=-12345, ring_numbers=(0, 1),
)
_TRACEABLE = (
    BatchedAlertMessage(sender=_EP1, messages=(_ALERT,)),
    FastRoundPhase2bMessage(sender=_EP1, configuration_id=7, endpoints=(_EP1, _EP2)),
    Phase1aMessage(sender=_EP1, configuration_id=7, rank=Rank(1, 2)),
    Phase1bMessage(sender=_EP1, configuration_id=7, rnd=Rank(1, 2),
                   vrnd=Rank(0, 0), vval=(_EP2,)),
    Phase2aMessage(sender=_EP1, configuration_id=7, rnd=Rank(1, 2), vval=(_EP2,)),
    Phase2bMessage(sender=_EP1, configuration_id=7, rnd=Rank(1, 2), endpoints=(_EP2,)),
)


def test_codec_trace_id_round_trip_and_absent_is_byte_identical():
    for bare in _TRACEABLE:
        traced = dataclasses.replace(bare, trace_id=0x1122334455667788)
        bare_bytes = encode_request(bare)
        traced_bytes = encode_request(traced)
        # Optional trailing field: absent = the pre-trace frame, present =
        # exactly 8 extra bytes appended.
        assert traced_bytes[: len(bare_bytes)] == bare_bytes, type(bare).__name__
        assert len(traced_bytes) == len(bare_bytes) + 8, type(bare).__name__
        assert decode_request(bare_bytes).trace_id is None
        out = decode_request(traced_bytes)
        assert out == bare  # trace_id is compare=False: protocol equality
        assert out.trace_id == 0x1122334455667788, type(bare).__name__


def test_codec_cache_distinguishes_trace_ids():
    # trace_id is excluded from dataclass equality/hash, so the encode LRU
    # must key on it explicitly — otherwise one message's cached bytes would
    # be replayed for an equal message carrying a different trace.
    base = _TRACEABLE[1]
    m1 = dataclasses.replace(base, trace_id=1)
    m2 = dataclasses.replace(base, trace_id=2)
    assert m1 == m2  # equal as protocol content...
    b1, b2 = encode_request(m1), encode_request(m2)
    assert b1 != b2  # ...but distinct on the wire
    assert decode_request(b1).trace_id == 1
    assert decode_request(b2).trace_id == 2


def test_proto_interop_drops_trace_id_without_changing_bytes():
    """The gRPC interop path: rapid.proto has no trace field (the golden
    fixtures freeze its descriptors), so a stamped trace id must not alter
    the proto frame — it travels as gRPC metadata instead and simply
    vanishes when talking to a reference peer."""
    from rapid_tpu.interop.convert import request_from_proto, request_to_proto

    for name, msg in canonical_requests().items():
        if not hasattr(msg, "trace_id"):
            continue
        traced = dataclasses.replace(msg, trace_id=0xBEEF)
        bare_frame = request_to_proto(msg).SerializeToString(deterministic=True)
        traced_frame = request_to_proto(traced).SerializeToString(deterministic=True)
        assert traced_frame == bare_frame, name
        assert request_from_proto(request_to_proto(traced)).trace_id is None, name


# ---------------------------------------------------------------------------
# exposition: stable Prometheus names (golden) and snapshot shape
# ---------------------------------------------------------------------------

#: The complete metric-name vocabulary of one node's scrape. This list is an
#: API: renaming or dropping an entry breaks every dashboard and alert rule
#: pointed at a rapid_tpu deployment, so any diff here must be deliberate.
#: (PR 2 deliberately re-shaped the timer surface: timers render as real
#: Prometheus histograms — ``_bucket``/``_sum``/``_count`` — instead of
#: stat-labeled summary gauges, and the phase-decomposed convergence SLO
#: family ``rapid_view_change_phase_ms`` plus the ``rapid_node_health``
#: one-hot joined the vocabulary.)
GOLDEN_METRIC_NAMES = [
    "rapid_alert_batches_redelivered_total",
    "rapid_alert_batches_sent_total",
    "rapid_alerts_enqueued_total",
    "rapid_alerts_received_total",
    "rapid_catch_up_wedged_total",
    "rapid_classic_rounds_started_total",
    "rapid_config_beacons_sent_total",
    "rapid_config_catch_ups_total",
    "rapid_config_pull_unchanged_served_total",
    "rapid_config_sync_unchanged_total",
    "rapid_configuration_id",
    "rapid_decision_missing_joiner_uuid_total",
    "rapid_flight_recorder_capacity",
    "rapid_flight_recorder_depth",
    "rapid_flight_recorder_dropped_total",
    "rapid_flight_recorder_recorded_total",
    "rapid_kicked_total",
    "rapid_membership_size",
    "rapid_node_health",
    "rapid_proposals_announced_total",
    "rapid_transport_bytes_rx_total",
    "rapid_transport_bytes_tx_total",
    "rapid_transport_kbps_rx",
    "rapid_transport_kbps_tx",
    "rapid_transport_msgs_rx_total",
    "rapid_transport_msgs_tx_total",
    "rapid_view_change_convergence_ms_bucket",
    "rapid_view_change_convergence_ms_count",
    "rapid_view_change_convergence_ms_sum",
    "rapid_view_change_phase_ms_bucket",
    "rapid_view_change_phase_ms_count",
    "rapid_view_change_phase_ms_sum",
    "rapid_view_changes_total",
]


def _hist_summary(*values_ms):
    hist = LogHistogram()
    for value in values_ms:
        hist.observe(value)
    return hist.summary()


def _full_synthetic_snapshot():
    transport_side = {
        "msgs_tx": 10, "bytes_tx": 1024, "msgs_rx": 9, "bytes_rx": 900,
        "elapsed_s": 2.0, "kbps_tx": 0.5, "kbps_rx": 0.44,
    }
    return {
        "node": "10.0.0.1:9001",
        "configuration_id": 42,
        "membership_size": 3,
        "health": "stable",
        "metrics": {
            "view_changes": 2,
            "view_change_convergence_ms": _hist_summary(12.0),
            "view_change_phase_ms": {
                "detection": _hist_summary(5.0),
                "agreement/fast": _hist_summary(4.0),
                "agreement/classic": _hist_summary(250.0),
                "delivery": _hist_summary(0.5),
            },
        },
        "transport": {"client": transport_side, "server": dict(transport_side)},
        "recorder": {
            "node": "10.0.0.1:9001", "capacity": 512,
            "recorded_total": 10, "dropped": 0, "events": [],
        },
    }


def test_prometheus_metric_names_are_golden():
    text = exposition.prometheus_text(_full_synthetic_snapshot())
    assert exposition.metric_names(text) == GOLDEN_METRIC_NAMES


def test_prometheus_text_values_and_labels():
    text = exposition.prometheus_text(_full_synthetic_snapshot())
    lines = text.splitlines()
    assert 'rapid_membership_size{node="10.0.0.1:9001"} 3' in lines
    assert 'rapid_view_changes_total{node="10.0.0.1:9001"} 2' in lines
    # Zero-filled vocabulary: series exist before their first increment.
    assert 'rapid_kicked_total{node="10.0.0.1:9001"} 0' in lines
    assert 'rapid_transport_bytes_tx_total{node="10.0.0.1:9001",side="client"} 1024' in lines
    assert 'rapid_transport_bytes_rx_total{node="10.0.0.1:9001",side="server"} 900' in lines
    # Health renders one-hot over the full vocabulary.
    assert 'rapid_node_health{node="10.0.0.1:9001",state="stable"} 1' in lines
    assert 'rapid_node_health{node="10.0.0.1:9001",state="wedged"} 0' in lines
    # Timers are real Prometheus histograms: _bucket/_sum/_count.
    assert 'rapid_view_change_convergence_ms_count{node="10.0.0.1:9001"} 1' in lines
    assert 'rapid_view_change_convergence_ms_sum{node="10.0.0.1:9001"} 12.0' in lines
    assert 'rapid_view_change_convergence_ms_bucket{node="10.0.0.1:9001",le="+Inf"} 1' in lines
    # The phase SLO family carries phase= (and path= for the agreement
    # split) labels — the tentpole's pinned series.
    assert 'rapid_view_change_phase_ms_bucket{phase="detection",node="10.0.0.1:9001",le="+Inf"} 1' in lines
    assert 'rapid_view_change_phase_ms_bucket{phase="agreement",path="fast",node="10.0.0.1:9001",le="+Inf"} 1' in lines
    assert 'rapid_view_change_phase_ms_count{phase="delivery",node="10.0.0.1:9001"} 1' in lines
    assert 'rapid_flight_recorder_depth{node="10.0.0.1:9001"} 10' in lines
    # Every metric is TYPE-declared exactly once — including one histogram
    # TYPE shared across the phase family's label sets.
    assert sum(1 for l in lines if l.startswith("# TYPE rapid_membership_size ")) == 1
    assert sum(
        1 for l in lines if l.startswith("# TYPE rapid_view_change_phase_ms ")
    ) == 1
    assert "# TYPE rapid_view_change_phase_ms histogram" in lines
    # Bucket lines are cumulative and end at the total count.
    detection = [
        l for l in lines
        if l.startswith('rapid_view_change_phase_ms_bucket{phase="detection"')
    ]
    counts = [int(l.rsplit(" ", 1)[1]) for l in detection]
    assert counts == sorted(counts) and counts[-1] == 1


def test_non_finite_values_render_spec_tokens():
    """Prometheus exposition tokens for non-finite floats are NaN/+Inf/-Inf;
    Python's repr ('nan', 'inf') is not scrapeable."""
    assert exposition._num(float("nan")) == "NaN"
    assert exposition._num(float("inf")) == "+Inf"
    assert exposition._num(float("-inf")) == "-Inf"
    assert exposition._num(1.5) == "1.5"
    assert exposition._num(7) == "7"
    snap = _full_synthetic_snapshot()
    snap["transport"]["client"]["kbps_tx"] = float("inf")
    snap["transport"]["client"]["kbps_rx"] = float("nan")
    lines = exposition.prometheus_text(snap).splitlines()
    assert 'rapid_transport_kbps_tx{node="10.0.0.1:9001",side="client"} +Inf' in lines
    assert 'rapid_transport_kbps_rx{node="10.0.0.1:9001",side="client"} NaN' in lines
    assert not any(l.endswith(" inf") or l.endswith(" nan") for l in lines)


def test_legacy_timer_dict_without_buckets_still_renders():
    # Old snapshot files (pre-histogram) carry {count,last,p50,max} only:
    # they fall back to the stat-labeled summary rendering instead of
    # crashing the scrape of an archived dump.
    snap = _full_synthetic_snapshot()
    snap["metrics"]["view_change_convergence_ms"] = {
        "count": 1, "last": 12.0, "p50": 12.0, "max": 12.0,
    }
    lines = exposition.prometheus_text(snap).splitlines()
    assert 'rapid_view_change_convergence_ms{node="10.0.0.1:9001",stat="p50"} 12.0' in lines


@async_test
async def test_live_cluster_snapshot_shape_and_prometheus():
    network = InProcessNetwork()
    clusters = await start_cluster(2, network)
    try:
        assert await wait_until(lambda: all_converged(clusters, 2))
        snap = clusters[0].telemetry_snapshot()
        assert snap["node"] == str(ep(0))
        assert snap["membership_size"] == 2
        assert snap["health"] in {s.value for s in NodeHealth}
        assert set(snap["transport"]) == {"client", "server"}
        assert snap["recorder"]["recorded_total"] > 0
        # The full snapshot (events included) is the --metrics-dump artifact.
        json.loads(exposition.snapshot_json(snap))

        text = clusters[0].prometheus_text()
        names = exposition.metric_names(text)
        # Live scrape exposes at least the golden vocabulary (extra counters
        # may appear as the node does more protocol work).
        assert set(GOLDEN_METRIC_NAMES) <= set(names)
        assert f'rapid_membership_size{{node="{ep(0)}"}} 2' in text.splitlines()
        # The seed proposed/decided/applied the join, so all three phases of
        # the convergence SLO surface are live — the tentpole's pinned claim.
        assert 'rapid_view_change_phase_ms_bucket{phase="detection"' in text
        assert 'rapid_view_change_phase_ms_bucket{phase="agreement"' in text
        assert 'rapid_view_change_phase_ms_bucket{phase="delivery"' in text
    finally:
        await shutdown_all(clusters)


@async_test
async def test_live_cluster_phase_decomposition_and_health():
    """A converged cluster's seed records all three convergence phases
    (detection closed at proposal release, agreement labeled by the deciding
    path, delivery closed at commit), and every node settles to STABLE
    health once the change is applied."""
    network = InProcessNetwork()
    clusters = await start_cluster(3, network)
    try:
        assert await wait_until(lambda: all_converged(clusters, 3))
        phases = clusters[0].metrics["view_change_phase_ms"]
        assert "detection" in phases and "delivery" in phases
        agreement = [k for k in phases if k.startswith("agreement/")]
        assert agreement and set(agreement) <= {"agreement/fast", "agreement/classic"}
        for summary in phases.values():
            assert summary["count"] >= 1
            assert summary["p50"] <= summary["p90"] <= summary["p99"]
            # Bounded histogram, not a sample list.
            assert sum(summary["buckets"].values()) == summary["count"]
        # Phase durations are sub-phases of the north-star timer: detection
        # through delivery on one change cannot exceed total convergence.
        conv = clusters[0].metrics["view_change_convergence_ms"]
        assert conv["count"] >= 1
        assert await wait_until(
            lambda: all(c.service.health() is NodeHealth.STABLE for c in clusters)
        )
        for c in clusters:
            assert c.telemetry_snapshot()["health"] == "stable"
    finally:
        await shutdown_all(clusters)


# ---------------------------------------------------------------------------
# config-sync pull stamping: compact "unchanged" vs reference compatibility
# ---------------------------------------------------------------------------


@async_test
async def test_catch_up_pull_config_id_depends_on_topology():
    """Native-topology pulls carry the requester's current config id (so an
    up-to-date in-tree peer answers with the compact "unchanged" response);
    java-topology pulls keep the joiner's -1 sentinel, because a reference
    JVM peer has no unchanged fast path — a config-id match there would park
    the response behind a never-decided UP alert instead of answering."""
    import random

    from rapid_tpu.messaging.inprocess import InProcessClient, InProcessServer
    from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
    from rapid_tpu.protocol.service import CATCH_UP_CONFIG_ID, MembershipService
    from rapid_tpu.protocol.view import MembershipView
    from rapid_tpu.settings import Settings
    from rapid_tpu.types import JoinMessage, JoinResponse, JoinStatusCode, NodeId

    async def pulled_config_id(topology):
        network = InProcessNetwork()
        settings = Settings()
        settings.topology = topology
        my, peer = Endpoint("127.0.0.1", 41000), Endpoint("127.0.0.1", 41001)
        view = MembershipView(
            settings.k, node_ids=[NodeId(0, 1), NodeId(0, 2)],
            endpoints=[my, peer], topology=topology,
        )
        service = MembershipService(
            my_addr=my,
            cut_detector=MultiNodeCutDetector(settings.k, settings.h, settings.l),
            view=view,
            settings=settings,
            client=InProcessClient(network, my, settings),
            fd_factory=StaticFailureDetectorFactory(),
            rng=random.Random(0),
            node_id=NodeId(0, 1),  # catch-up authenticates by endpoint + id
        )
        seen = []

        class _Peer:
            async def handle_message(self, request):
                seen.append(request)
                return JoinResponse(
                    sender=peer,
                    status_code=JoinStatusCode.CONFIG_CHANGED,
                    configuration_id=request.configuration_id,
                )

        server = InProcessServer(network, peer)
        server.set_membership_service(_Peer())
        await server.start()
        try:
            await service._catch_up(peer)
        finally:
            await server.shutdown()
            await service.shutdown()
        [msg] = [m for m in seen if isinstance(m, JoinMessage)]
        return msg.configuration_id, service.view.configuration_id

    sent, current = await pulled_config_id("native")
    assert sent == current
    sent, current = await pulled_config_id("java")
    assert sent == CATCH_UP_CONFIG_ID != current


# ---------------------------------------------------------------------------
# traceview: merge order and Chrome trace output
# ---------------------------------------------------------------------------


def test_merge_orders_timestamp_ties_by_protocol_phase():
    clock = ManualClock()  # both nodes on one simulated instant
    rec_a = FlightRecorder(node="a", clock=clock, capacity=8)
    rec_b = FlightRecorder(node="b", clock=clock, capacity=8)
    rec_a.record(EventName.CONSENSUS_DECIDED, config_id=1, trace_id=9)
    rec_b.record(EventName.ALERT_ENQUEUED, config_id=1, trace_id=9)
    rec_b.record(EventName.FAST_ROUND_PROPOSAL, config_id=1, trace_id=9)
    merged = traceview.merge_events([rec_a.snapshot(), rec_b.snapshot()])
    assert [e["name"] for e in merged] == [
        "alert_enqueued", "fast_round_proposal", "consensus_decided",
    ]


def test_merge_filters_by_trace_id():
    clock = ManualClock()
    rec = FlightRecorder(node="a", clock=clock, capacity=8)
    rec.record(EventName.ALERT_ENQUEUED, trace_id=1)
    rec.record(EventName.ALERT_ENQUEUED, trace_id=2)
    merged = traceview.merge_events([rec.snapshot()], trace_id=2)
    assert len(merged) == 1 and merged[0]["trace_id"] == 2


def _first_index(events, node, names):
    for i, e in enumerate(events):
        if e["node"] == node and e["name"] in names:
            return i
    raise AssertionError(f"no {names} event for {node}")


@async_test
async def test_traceview_merges_three_node_crash_and_converge():
    """The tentpole's end-to-end criterion: a 3-node cluster crashes one
    member, converges, and the per-node flight recordings merge into one
    causally-ordered timeline — alert → proposal → decision → delivery on
    every surviving node, all three nodes present — that renders as valid
    Chrome trace-event JSON."""
    network = InProcessNetwork()
    fd = StaticFailureDetectorFactory()
    clusters = await start_cluster(3, network, fd_factory=fd)
    victim, survivors = clusters[2], clusters[:2]
    try:
        assert await wait_until(lambda: all_converged(clusters, 3))
        network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([victim.listen_address])
        assert await wait_until(lambda: all_converged(survivors, 2))

        with tempfile.TemporaryDirectory() as tmp:
            paths = []
            for i, c in enumerate(clusters):
                path = str(Path(tmp) / f"node{i}.json")
                with open(path, "w") as f:
                    f.write(exposition.snapshot_json(c.telemetry_snapshot()))
                paths.append(path)
            chrome_path = str(Path(tmp) / "chrome.json")
            assert traceview.main([*paths, "--chrome", chrome_path]) == 0
            merged = traceview.merge_events(traceview.load_snapshots(paths))
            with open(chrome_path) as f:
                chrome = json.load(f)

        # Every node of the cluster contributes to the merged timeline (the
        # victim's recording covers the pre-crash join epochs).
        assert {e["node"] for e in merged} == {str(c.listen_address) for c in clusters}

        for c in survivors:
            node = str(c.listen_address)
            # The final view change on this node is the victim's eviction;
            # its trace id correlates that change's events across phases.
            view_changes = [
                e for e in merged
                if e["node"] == node and e["name"] == "view_change"
            ]
            assert view_changes, node
            trace = view_changes[-1]["trace_id"]
            assert trace is not None, node
            chain = [e for e in merged if e["node"] == node and e["trace_id"] == trace]
            alert = _first_index(chain, node, ("alert_enqueued", "alert_batch_rx"))
            proposal = _first_index(chain, node, ("fast_round_proposal",))
            decided = _first_index(chain, node, ("consensus_decided",))
            delivered = _first_index(chain, node, ("view_change",))
            assert alert < proposal < decided < delivered, (
                node, [(e["name"], e["t_ms"]) for e in chain],
            )

        # Chrome trace-event validity: the envelope Perfetto/chrome://tracing
        # load, instant events with µs timestamps, metadata naming each node.
        assert set(chrome) == {"traceEvents", "displayTimeUnit"}
        assert chrome["displayTimeUnit"] == "ms"
        process_names = set()
        instants = 0
        for ev in chrome["traceEvents"]:
            assert {"name", "ph", "pid", "tid"} <= set(ev)
            assert ev["ph"] in ("M", "i")
            if ev["ph"] == "M":
                if ev["name"] == "process_name":
                    process_names.add(ev["args"]["name"])
            else:
                instants += 1
                assert ev["s"] == "t"
                assert isinstance(ev["ts"], (int, float))
        assert process_names == {str(c.listen_address) for c in clusters}
        assert instants == len(merged)
    finally:
        await shutdown_all(clusters)


# ---------------------------------------------------------------------------
# traceview CLI error handling: clean nonzero exits, never tracebacks
# ---------------------------------------------------------------------------


def test_traceview_errors_cleanly_on_invalid_json(tmp_path, capsys):
    bad = tmp_path / "bad.json"
    bad.write_text("{this is not json")
    assert traceview.main([str(bad)]) == 2
    err = capsys.readouterr().err
    assert "traceview:" in err and str(bad) in err and "invalid JSON" in err


def test_traceview_errors_cleanly_on_unreadable_file(tmp_path, capsys):
    missing = tmp_path / "does_not_exist.json"
    assert traceview.main([str(missing)]) == 2
    err = capsys.readouterr().err
    assert "traceview:" in err and str(missing) in err


def test_traceview_errors_cleanly_on_non_snapshot_json(tmp_path, capsys):
    scalar = tmp_path / "scalar.json"
    scalar.write_text("42")
    assert traceview.main([str(scalar)]) == 2
    assert "not a telemetry snapshot" in capsys.readouterr().err


def test_traceview_errors_cleanly_on_zero_events(tmp_path, capsys):
    # A dump taken with recorder_tail=0 (e.g. a Prometheus-oriented scrape)
    # holds no events: the merge has nothing to order, and the CLI must say
    # so instead of printing an empty timeline and exiting 0.
    rec = FlightRecorder(node="a", clock=ManualClock(), capacity=4)
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps(rec.snapshot()))
    assert traceview.main([str(empty)]) == 2
    assert "no recorder events" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# clustertop: the live cluster health/SLO dashboard
# ---------------------------------------------------------------------------


def _clustertop_snapshot(node, health="stable", detection_ms=(), config=7):
    metrics = {"view_changes": 1}
    if detection_ms:
        hist = LogHistogram()
        for v in detection_ms:
            hist.observe(v)
        metrics["view_change_phase_ms"] = {"detection": hist.summary()}
    return {
        "node": node,
        "configuration_id": config,
        "membership_size": 3,
        "health": health,
        "metrics": metrics,
        "transport": {"client": {"kbps_tx": 1.25, "kbps_rx": 0.75}},
        "recorder": None,
    }


def test_aggregate_health_worst_state_wins_with_stable_counts():
    agg = aggregate_health(["stable", "detecting", "stable"])
    assert agg["overall"] == "detecting"
    assert agg["counts"]["stable"] == 2 and agg["counts"]["detecting"] == 1
    assert set(agg["counts"]) == {s.value for s in NodeHealth}  # zero-filled
    assert aggregate_health([])["overall"] == "stable"
    # Unknown/legacy values read as stable, never as an invented state.
    assert aggregate_health(["???", None])["overall"] == "stable"
    assert aggregate_health(["stable", "WEDGED"])["overall"] == "wedged"


def test_clustertop_renders_health_and_merged_phase_quantiles():
    snapshots = [
        _clustertop_snapshot("10.0.0.1:9001", "stable", detection_ms=(5.0, 6.0)),
        _clustertop_snapshot("10.0.0.2:9001", "proposing", detection_ms=(50.0,)),
        _clustertop_snapshot("10.0.0.3:9001", "wedged"),
    ]
    frame = clustertop.render_frame(snapshots)
    assert "3 node(s)" in frame
    assert "health: WEDGED" in frame  # worst state present wins the header
    assert "1 wedged" in frame and "1 proposing" in frame and "1 stable" in frame
    for node in ("10.0.0.1:9001", "10.0.0.2:9001", "10.0.0.3:9001"):
        assert node in frame
    # Cluster-wide SLO line comes from MERGED per-node histograms: three
    # detection samples total, p99 in the bucket holding the 50 ms sample.
    merged = LogHistogram()
    for v in (5.0, 6.0, 50.0):
        merged.observe(v)
    assert f"detection p50={merged.quantile(0.5):.1f} p99={merged.quantile(0.99):.1f}" in frame
    # A wedged node with no phase data renders dashes, not a crash.
    assert "wedged" in frame


def test_clustertop_renders_three_node_dump_files(tmp_path, capsys):
    # The acceptance path: >=3 per-node snapshot dumps on disk -> one frame.
    paths = []
    for i in range(3):
        path = tmp_path / f"node{i}.json"
        path.write_text(json.dumps(_clustertop_snapshot(f"10.0.0.{i + 1}:9001")))
        paths.append(str(path))
    torn = tmp_path / "torn.json"
    torn.write_text('{"node": "10.0.0.9:9001"')  # mid-rewrite file
    assert clustertop.main([*paths, str(torn), "--once"]) == 0
    out = capsys.readouterr().out
    for i in range(3):
        assert f"10.0.0.{i + 1}:9001" in out
    assert "3 node(s)" in out
    assert "torn.json" in out  # degraded to a footnote, not a crash
    assert "configs: 1 (agreement)" in out


def test_clustertop_once_with_nothing_renderable_exits_nonzero(tmp_path, capsys):
    bad = tmp_path / "bad.json"
    bad.write_text("nope")
    assert clustertop.main([str(bad), "--once"]) == 2
    assert "invalid JSON" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# phase-mark hygiene: stale evidence and uncommittable decisions must not
# corrupt the phase histograms
# ---------------------------------------------------------------------------


def _direct_service(clock, n=3):
    """A MembershipService wired directly (no started loops): the harness the
    phase-mark regression tests drive synchronously."""
    import random

    from rapid_tpu.messaging.inprocess import InProcessClient
    from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
    from rapid_tpu.protocol.service import MembershipService
    from rapid_tpu.protocol.view import MembershipView
    from rapid_tpu.settings import Settings
    from rapid_tpu.types import NodeId

    settings = Settings()
    endpoints = [Endpoint("127.0.0.1", 42000 + i) for i in range(n)]
    node_ids = [NodeId(0, i + 1) for i in range(n)]
    view = MembershipView(settings.k, node_ids=node_ids, endpoints=endpoints)
    service = MembershipService(
        my_addr=endpoints[0],
        cut_detector=MultiNodeCutDetector(settings.k, settings.h, settings.l),
        view=view,
        settings=settings,
        client=InProcessClient(InProcessNetwork(), endpoints[0], settings),
        fd_factory=StaticFailureDetectorFactory(),
        clock=clock,
        rng=random.Random(0),
        node_id=node_ids[0],
    )
    return service, endpoints, settings


@async_test
async def test_stale_detection_mark_does_not_inflate_phase_histogram():
    """A spurious alert that never produces a view change leaves a detection
    mark behind; a genuine change hours later must re-open the detection
    epoch (same staleness policy as the convergence timer), not record the
    hours-old mark into the phase histogram."""
    clock = ManualClock()
    service, endpoints, settings = _direct_service(clock)
    try:
        me, b, c = endpoints

        def batch(rings):
            return BatchedAlertMessage(
                sender=b,
                messages=(AlertMessage(
                    edge_src=b, edge_dst=c, edge_status=EdgeStatus.DOWN,
                    configuration_id=service.view.configuration_id,
                    ring_numbers=tuple(rings),
                ),),
            )

        # One below-L report: detection mark armed, no proposal follows.
        service._handle_batched_alerts(batch([0]))
        assert not service._announced_proposal
        ten_hours_ms = 10 * 3600 * 1000.0
        clock.advance_ms(ten_hours_ms)
        # The genuine change: reports cross H in one batch -> proposal.
        service._handle_batched_alerts(batch(range(settings.h)))
        assert service._announced_proposal
        detection = service.metrics.phase_timings["view_change_phase"]["detection"]
        assert detection.count == 1
        assert detection.max <= service._stale_evidence_ms(), detection.max
    finally:
        await service.shutdown()


@async_test
async def test_recovery_path_does_not_arm_delivery_mark():
    """A decision naming a joiner whose UP alert was lost takes the
    catch-up recovery path and never commits: the delivery mark must not be
    armed, or the eventual catch-up install would charge the whole
    multi-second recovery pull to the 'delivery' phase."""
    clock = ManualClock()
    service, endpoints, _ = _direct_service(clock)
    try:
        unknown_joiner = Endpoint("127.0.0.1", 42999)
        service._decide_view_change((unknown_joiner,))
        assert service._decision_pending_catch_up  # recovery engaged
        assert not service.metrics.has_mark("vc_phase_delivery")
        # And no delivery sample was recorded by the aborted decision.
        family = service.metrics.phase_timings.get("view_change_phase", {})
        assert "delivery" not in family
    finally:
        await service.shutdown()
