"""Exercise the multi-host entry (rapid_tpu.parallel.multihost): a real
single-process ``jax.distributed`` job — coordinator bring-up, global mesh
construction, and a sharded engine step over that mesh — so the DCN-story
module runs under test, not just its argument handling.

``jax.distributed.initialize`` must run before ANY backend initialization, so
the job executes in a fresh subprocess (the rest of the suite has long since
initialized the in-process CPU backend).
"""

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    """Kernel-assigned coordinator port: a fixed port collides with a
    lingering coordinator from a killed run (or a parallel session) and
    flakes the whole job at bind time."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# Capability probe: some containers' jaxlib builds reject cross-process
# collectives outright ("Multiprocess computations aren't implemented on the
# CPU backend") — the two-process test then fails identically on the
# pristine seed every run. Probe once per session with a minimal two-process
# allgather job and skip (with the probe's own diagnostic) instead of
# re-reporting a known environment gap as a code failure.
_PROBE_JOB = """
import sys
process_id = int(sys.argv[1])
port = int(sys.argv[2])
from rapid_tpu.utils.platform import force_platform
assert force_platform("cpu", n_host_devices=2)
import jax
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=process_id,
)
try:
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    out = multihost_utils.process_allgather(jnp.int32(process_id))
    assert out.sum() == 1, out
    print(f"PROBE_OK_{process_id}")
finally:
    jax.distributed.shutdown()
"""

_probe_result = None  # (supported: bool, detail: str), cached per session


def _multiprocess_cpu_supported():
    global _probe_result
    if _probe_result is not None:
        return _probe_result
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{REPO}:" + env.get("PYTHONPATH", "")
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE_JOB, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=REPO,
        )
        for pid in (0, 1)
    ]
    deadline = time.monotonic() + 120
    while any(p.poll() is None for p in procs) and time.monotonic() < deadline:
        time.sleep(0.5)
    timed_out = any(p.poll() is None for p in procs)
    for p in procs:
        if p.poll() is None:
            p.kill()
    outputs = [p.communicate()[0] for p in procs]
    ok = (
        not timed_out
        and all(p.returncode == 0 for p in procs)
        and all(f"PROBE_OK_{pid}" in out for pid, out in enumerate(outputs))
    )
    if ok:
        detail = "supported"
    else:
        tails = " | ".join(
            (out.strip().splitlines() or ["(no output)"])[-1] for out in outputs
        )
        detail = "probe timed out" if timed_out else tails
    _probe_result = (ok, detail)
    return _probe_result

_JOB = """
import sys
import numpy as np
from rapid_tpu.utils.platform import force_platform

assert force_platform("cpu", n_host_devices=8)

import jax

from rapid_tpu.parallel import multihost

multihost.initialize_multihost(
    coordinator_address=f"127.0.0.1:{sys.argv[1]}", num_processes=1, process_id=0
)
try:
    assert multihost.is_coordinator()
    assert multihost.local_device_count() == 8

    from rapid_tpu.models.virtual_cluster import VirtualCluster
    from rapid_tpu.parallel.mesh import make_sharded_step, shard_faults, shard_state

    mesh = multihost.global_mesh()
    assert mesh.devices.size == 8

    vc = VirtualCluster.create(60, n_slots=64, fd_threshold=2, seed=0)
    vc.crash([3, 17])
    step = make_sharded_step(vc.cfg, mesh)
    state = shard_state(vc.state, mesh)
    faults = shard_faults(vc.faults, mesh)
    decided = False
    for _ in range(16):
        state, events = step(state, faults)
        if bool(events.decided):
            decided = True
            break
    assert decided
    alive = np.asarray(state.alive)
    assert not alive[[3, 17]].any()
    assert int(state.n_members) == 58
    print("MULTIHOST_JOB_OK")
finally:
    jax.distributed.shutdown()
"""


def test_single_process_distributed_job_runs_sharded_step():
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{REPO}:" + env.get("PYTHONPATH", "")
    result = subprocess.run(
        [sys.executable, "-c", _JOB, str(_free_port())],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
        cwd=REPO,
    )
    assert result.returncode == 0, f"job failed:\n{result.stdout}\n{result.stderr}"
    assert "MULTIHOST_JOB_OK" in result.stdout


# Real multi-controller: TWO processes, 4 virtual CPU devices each, one
# global 8-device mesh. Each process computes identical host state
# (deterministic seed), assembles globally-sharded arrays from its own
# addressable shards (shard_host_pytree), and runs the SPMD engine step —
# the actual DCN execution model, with cross-process collectives for the
# engine's global reductions.
_JOB2 = """
import sys
process_id = int(sys.argv[1])
coordinator_port = int(sys.argv[2])

from rapid_tpu.utils.platform import force_platform
assert force_platform("cpu", n_host_devices=4)

import jax
from rapid_tpu.parallel import multihost

multihost.initialize_multihost(
    coordinator_address=f"127.0.0.1:{coordinator_port}",
    num_processes=2, process_id=process_id,
)
try:
    assert jax.process_count() == 2
    assert multihost.local_device_count() == 4
    assert len(jax.devices()) == 8

    from rapid_tpu.models.virtual_cluster import VirtualCluster
    from rapid_tpu.parallel.mesh import (
        fault_shardings,
        make_sharded_step,
        state_shardings,
    )

    mesh = multihost.global_mesh()
    assert mesh.devices.size == 8

    vc = VirtualCluster.create(60, n_slots=64, fd_threshold=2, seed=0)
    vc.crash([3, 17])
    step = make_sharded_step(vc.cfg, mesh)
    state = multihost.shard_host_pytree(vc.state, state_shardings(mesh))
    faults = multihost.shard_host_pytree(vc.faults, fault_shardings(mesh))
    decided = False
    for _ in range(16):
        state, events = step(state, faults)
        if bool(events.decided):  # replicated scalar: addressable everywhere
            decided = True
            break
    assert decided
    assert int(state.n_members) == 58
    from jax.experimental import multihost_utils

    alive = multihost_utils.process_allgather(state.alive, tiled=True)
    assert not alive[[3, 17]].any()
    assert alive.sum() == 58
    print(f"MULTIHOST2_OK_{process_id}")
finally:
    jax.distributed.shutdown()
"""


def test_two_process_distributed_job_runs_sharded_step():
    supported, detail = _multiprocess_cpu_supported()
    if not supported:
        pytest.skip(f"multiprocess CPU computations unavailable here: {detail}")
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{REPO}:" + env.get("PYTHONPATH", "")
    port = _free_port()  # both processes must agree on the coordinator
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _JOB2, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=REPO,
        )
        for pid in (0, 1)
    ]
    # Drain as processes exit rather than sequentially: if one crashes at
    # init the other blocks at the distributed barrier, and a sequential
    # communicate() on the hung one would time out WITHOUT ever reading the
    # crashed one's traceback — the diagnostic that matters.
    deadline = time.monotonic() + 240
    while any(p.poll() is None for p in procs) and time.monotonic() < deadline:
        time.sleep(0.5)
    for p in procs:
        if p.poll() is None:
            p.kill()
    outputs = [p.communicate()[0] for p in procs]
    for pid, (proc, out) in enumerate(zip(procs, outputs)):
        all_out = "\n".join(
            f"--- process {i} (rc={q.returncode}) ---\n{o}"
            for i, (q, o) in enumerate(zip(procs, outputs))
        )
        assert proc.returncode == 0, f"process {pid} failed:\n{all_out}"
        assert f"MULTIHOST2_OK_{pid}" in out, all_out
