"""Exercise the multi-host entry (rapid_tpu.parallel.multihost): a real
single-process ``jax.distributed`` job — coordinator bring-up, global mesh
construction, and a sharded engine step over that mesh — so the DCN-story
module runs under test, not just its argument handling.

``jax.distributed.initialize`` must run before ANY backend initialization, so
the job executes in a fresh subprocess (the rest of the suite has long since
initialized the in-process CPU backend).
"""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

_JOB = """
import numpy as np
from rapid_tpu.utils.platform import force_platform

assert force_platform("cpu", n_host_devices=8)

import jax

from rapid_tpu.parallel import multihost

multihost.initialize_multihost(
    coordinator_address="127.0.0.1:47310", num_processes=1, process_id=0
)
try:
    assert multihost.is_coordinator()
    assert multihost.local_device_count() == 8

    from rapid_tpu.models.virtual_cluster import VirtualCluster
    from rapid_tpu.parallel.mesh import make_sharded_step, shard_faults, shard_state

    mesh = multihost.global_mesh()
    assert mesh.devices.size == 8

    vc = VirtualCluster.create(60, n_slots=64, fd_threshold=2, seed=0)
    vc.crash([3, 17])
    step = make_sharded_step(vc.cfg, mesh)
    state = shard_state(vc.state, mesh)
    faults = shard_faults(vc.faults, mesh)
    decided = False
    for _ in range(16):
        state, events = step(state, faults)
        if bool(events.decided):
            decided = True
            break
    assert decided
    alive = np.asarray(state.alive)
    assert not alive[[3, 17]].any()
    assert int(state.n_members) == 58
    print("MULTIHOST_JOB_OK")
finally:
    jax.distributed.shutdown()
"""


def test_single_process_distributed_job_runs_sharded_step():
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{REPO}:" + env.get("PYTHONPATH", "")
    result = subprocess.run(
        [sys.executable, "-c", _JOB],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
        cwd=REPO,
    )
    assert result.returncode == 0, f"job failed:\n{result.stdout}\n{result.stderr}"
    assert "MULTIHOST_JOB_OK" in result.stdout
