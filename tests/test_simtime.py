"""Deterministic simulated-time cluster runs: every timing consumer (batcher,
failure detectors, consensus fallback) goes through the Clock abstraction, so
a ManualClock drives whole failure-detection -> consensus sequences in
virtual milliseconds with zero wall-clock sleeps."""

import asyncio
import functools
import random

from rapid_tpu.messaging.inprocess import InProcessNetwork
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint
from rapid_tpu.utils.clock import ManualClock


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=60)

        asyncio.run(with_timeout())

    return wrapper


async def drain(loop_yields=50):
    for _ in range(loop_yields):
        await asyncio.sleep(0)


async def advance(clock: ManualClock, total_ms: float, step_ms: float = 50):
    """Advance simulated time, yielding to the loop between steps so woken
    coroutines actually run."""
    advanced = 0.0
    while advanced < total_ms:
        clock.advance_ms(step_ms)
        advanced += step_ms
        await drain()


@async_test
async def test_crash_detection_in_simulated_time():
    settings = Settings()  # reference-default timings: 1 s FD, 100 ms batching
    network = InProcessNetwork()
    clock = ManualClock()
    fd = StaticFailureDetectorFactory()
    clusters = [
        await Cluster.start(Endpoint("127.0.0.1", 32000), settings=settings, network=network,
                            fd_factory=fd, clock=clock, rng=random.Random(0))
    ]
    # Joins block on consensus, which blocks on virtual batching windows:
    # run them as tasks while time advances.
    for i in range(1, 6):
        join_task = asyncio.ensure_future(
            Cluster.join(Endpoint("127.0.0.1", 32000), Endpoint("127.0.0.1", 32000 + i),
                         settings=settings, network=network, fd_factory=fd,
                         clock=clock, rng=random.Random(i))
        )
        while not join_task.done():
            await advance(clock, 200)
        clusters.append(join_task.result())
    assert all(c.membership_size == 6 for c in clusters)

    victim = clusters[3]
    network.blackholed.add(victim.listen_address)
    fd.add_failed_nodes([victim.listen_address])
    survivors = [c for c in clusters if c is not victim]

    # One FD interval surfaces the failure; one batching window broadcasts it;
    # consensus follows instantly in-process. Give 3 simulated seconds.
    sim_before = clock.now_ms()
    await advance(clock, 3_000)
    assert all(c.membership_size == 5 for c in survivors)
    assert len({tuple(c.membership) for c in survivors}) == 1
    # No wall-clock dependence: simulated now is exactly what we advanced.
    assert clock.now_ms() == sim_before + 3_000

    for c in clusters:
        await c.shutdown()


@async_test
async def test_fallback_timer_is_virtual():
    # The consensus fallback delay (>= 1 s simulated) must not consume wall
    # time: schedule and cancel entirely in virtual milliseconds.
    from rapid_tpu.protocol.fast_paxos import FastPaxos

    clock = ManualClock()
    fired = []
    fp = FastPaxos(
        my_addr=Endpoint("127.0.0.1", 1),
        configuration_id=1,
        membership_size=5,
        broadcast_fn=lambda r: None,
        send_fn=lambda d, r: None,
        on_decide=lambda hosts: None,
        clock=clock,
        rng=random.Random(0),
    )
    fp.start_classic_paxos_round = lambda: fired.append(True)  # type: ignore[method-assign]
    fp.propose((Endpoint("127.0.0.1", 9),), recovery_delay_ms=4_000)
    clock.advance_ms(3_999)
    assert not fired
    clock.advance_ms(2)
    assert fired == [True]
