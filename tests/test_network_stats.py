"""Per-node network accounting (paper Table 2 analog).

The reference's evaluation reports per-process network use during the
crash experiment (Rapid mean 0.71/0.71 KB/s rx/tx, max 9.56/11.37 —
paper Table 2) using external OS instrumentation; every transport here
carries ``TransportStats`` so the measurement is a library call. These
tests pin the accounting itself and the two structural laws behind the
paper's numbers: steady-state monitoring traffic is O(K) per node
regardless of N, and the gossip broadcaster caps per-node egress at
O(fanout) where unicast-to-all pays O(N) at the sender.
"""

import asyncio
import random

from tests.test_cluster import (
    all_converged,
    async_test,
    ep,
    fast_settings,
    shutdown_all,
    start_cluster,
)

from rapid_tpu.messaging.inprocess import InProcessNetwork
from rapid_tpu.messaging.tcp import TcpClient, TcpServer
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.types import Endpoint, ProbeMessage, ProbeResponse

from tests.helpers import wait_until


def test_snapshot_rates():
    from rapid_tpu.messaging.stats import TransportStats

    s = TransportStats()
    s.tx(512)
    s.tx(512)
    s.rx(2048)
    snap = s.snapshot()
    assert snap["msgs_tx"] == 2 and snap["bytes_tx"] == 1024
    assert snap["msgs_rx"] == 1 and snap["bytes_rx"] == 2048
    assert snap["kbps_tx"] > 0 and snap["elapsed_s"] >= 0
    s.reset_window()
    assert s.snapshot()["msgs_tx"] == 0


@async_test
async def test_tcp_transport_counts_real_wire_bytes():
    server = TcpServer(Endpoint("127.0.0.1", 0))  # ephemeral port

    class _Probes:
        async def handle_message(self, request):
            return ProbeResponse()

    server.set_membership_service(_Probes())
    await server.start()
    server_addr = server.listen_address  # kernel-assigned
    client = TcpClient(Endpoint("127.0.0.1", 0))
    try:
        for _ in range(3):
            await client.send(server_addr, ProbeMessage(sender=client.my_addr))
        c, s = client.stats.snapshot(), server.stats.snapshot()
        assert c["msgs_tx"] == 3 and c["msgs_rx"] == 3
        assert s["msgs_rx"] == 3 and s["msgs_tx"] == 3
        # Byte symmetry: what the client framed is what the server read.
        assert c["bytes_tx"] == s["bytes_rx"] > 3 * 13  # 13 = frame header
        assert c["bytes_rx"] == s["bytes_tx"] > 3 * 13
    finally:
        await client.shutdown()
        await server.shutdown()


@async_test
async def test_unified_snapshot_exposes_transport_byte_counters():
    """The exposition layer (utils/exposition.py) must surface this module's
    accounting: a node's unified telemetry snapshot carries both transport
    sides' byte/message counters, and the Prometheus rendering exposes them
    under the stable rapid_transport_* names."""
    from rapid_tpu.utils import exposition

    network = InProcessNetwork(count_wire_bytes=True)
    clusters = await start_cluster(3, network)
    try:
        assert await wait_until(lambda: all_converged(clusters, 3))
        for c in clusters:
            snap = c.telemetry_snapshot()
            client, server = snap["transport"]["client"], snap["transport"]["server"]
            # Three nodes converged: every node sent traffic, and
            # wire-equivalent byte accounting is on. (A node's SERVER can be
            # legitimately silent — with static FDs nothing probes the last
            # joiner — so the rx law is asserted on the seed, which every
            # join traversed.)
            assert client["msgs_tx"] > 0 and client["bytes_tx"] > 0
            assert server["msgs_rx"] >= 0 and server["bytes_rx"] >= 0
            text = c.prometheus_text()
            names = exposition.metric_names(text)
            for key in ("msgs_tx", "bytes_tx", "msgs_rx", "bytes_rx"):
                assert f"rapid_transport_{key}_total" in names
        seed_server = clusters[0].telemetry_snapshot()["transport"]["server"]
        assert seed_server["msgs_rx"] > 0 and seed_server["bytes_rx"] > 0
    finally:
        await shutdown_all(clusters)


@async_test
async def test_steady_state_traffic_is_o_k_per_node():
    """Monitoring load per node tracks K (its observers x probe rate), not
    N — the expander property that keeps Table 2's per-process numbers flat
    as the cluster grows (MembershipView.java:41-45)."""
    network = InProcessNetwork(count_wire_bytes=True)
    settings = fast_settings()
    # Default (ping-pong) failure detectors: steady-state traffic IS the
    # probe stream, which is what Table 2 measures.
    clusters = [
        await Cluster.start(ep(0), settings=settings, network=network,
                            rng=random.Random(0))
    ]
    for i in range(1, 10):
        clusters.append(
            await Cluster.join(ep(0), ep(i), settings=settings,
                               network=network, rng=random.Random(i))
        )
    try:
        for c in clusters:
            c._client.stats.reset_window()
        interval_s = settings.failure_detector_interval_ms / 1000.0
        ticks = 6
        await asyncio.sleep(ticks * interval_s)
        k = settings.k
        for c in clusters:
            snap = c._client.stats.snapshot()
            # Each node probes its <= K subjects once per FD interval (plus
            # slack for batcher/in-flight rounding). With N=10 < K=10 every
            # node monitors all 9 others; the bound is K per tick either way.
            # Derive the tick count from the window's OBSERVED elapsed time:
            # under CI load the sleep can overshoot and extra FD ticks fire
            # before the snapshot — the law is per-elapsed-tick, not
            # per-nominal-tick.
            observed_ticks = snap["elapsed_s"] / interval_s
            assert 0 < snap["msgs_tx"] <= (observed_ticks + 2) * k, snap
            assert snap["bytes_tx"] > 0  # wire-equivalent accounting is on
    finally:
        await shutdown_all(clusters)


@async_test
async def test_gossip_caps_sender_egress_where_unicast_pays_n():
    """The gossip broadcaster's load-spreading law (paper §7): for ONE
    broadcast, the unicast sender's egress is O(N) while no gossip node —
    origin included — ever sends more than fanout+1 envelopes. (This is
    specifically a SENDER-load property: when every node broadcasts at
    once, e.g. a round of consensus votes, unicast is per-node optimal and
    gossip pays its redundancy factor — which is why gossip is the
    pluggable alternative, not the default, exactly as in the reference's
    IBroadcaster docs.)"""
    from rapid_tpu.messaging.base import UnicastToAllBroadcaster
    from rapid_tpu.messaging.inprocess import InProcessClient, InProcessServer
    from rapid_tpu.settings import Settings
    from tests.test_gossip import (
        RecordingService,
        build_mesh,
        teardown_mesh,
    )

    n = 24

    # Unicast: one broadcast costs the sender N sends, everyone else 0.
    network = InProcessNetwork()
    servers, services = [], []
    for i in range(n):
        server = InProcessServer(network, ep(i))
        service = RecordingService()
        server.set_membership_service(service)
        await server.start()
        servers.append(server)
        services.append(service)
    sender = InProcessClient(network, ep(0), Settings())
    unicaster = UnicastToAllBroadcaster(sender, rng=random.Random(1))
    unicaster.set_membership([ep(i) for i in range(n)])
    unicaster.broadcast(ProbeMessage(sender=ep(0)))
    await wait_until(lambda: sum(len(s.received) for s in services) >= n)
    unicast_sender_tx = sender.stats.msgs_tx
    await asyncio.gather(*(s.shutdown() for s in servers), sender.shutdown())

    # Gossip: the same single broadcast spreads epidemically; every node's
    # egress (relays + the origin's self-delivery) stays <= fanout + 1.
    fanout = 4
    gnetwork, nodes = await build_mesh(n, fanout=fanout)
    del gnetwork
    try:
        nodes[0][3].broadcast(ProbeMessage(sender=ep(0)))
        await wait_until(
            lambda: sum(len(svc.received) for _, _, svc, _ in nodes) >= n
        )
        per_node_tx = [client.stats.msgs_tx for client, _, _, _ in nodes]
        assert unicast_sender_tx == n
        assert max(per_node_tx) <= fanout + 1, per_node_tx
        assert max(per_node_tx) < unicast_sender_tx
    finally:
        await teardown_mesh(nodes)
