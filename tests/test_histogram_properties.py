"""Property-based invariants of the bounded log-bucketed histogram
(utils/histogram.py) — the algebra the cluster-wide SLO story leans on:

- **merge is associative and commutative** (over counts/sum/max/buckets —
  the mergeable state; ``last`` is an explicitly order-dependent display
  nicety), so folding per-node snapshots into one cluster histogram gives
  the same answer in any order and any grouping (tools/clustertop.py);
- **quantile rank bounds**: quantile(q) is never below the true order
  statistic and never more than one bucket (GROWTH) above it — the error
  contract every dashboard percentile inherits;
- **conservation**: count and sum equal the recorded samples' count and sum
  exactly, through merges and the summary/from_summary round trip.
"""

import math

import pytest

pytest.importorskip("hypothesis")  # property tests need it; the rest of the suite doesn't
from hypothesis import given, settings, strategies as st

from rapid_tpu.utils.histogram import (
    FIRST_UPPER_MS,
    GROWTH,
    LogHistogram,
)

# Durations spanning the whole schedule: sub-first-bucket to past-overflow.
_SAMPLES = st.lists(
    st.floats(min_value=0.0, max_value=1e9, allow_nan=False, allow_infinity=False),
    max_size=60,
)


def _hist(samples):
    hist = LogHistogram()
    for s in samples:
        hist.observe(s)
    return hist


def _assert_same_mergeable_state(a, b):
    """Equality over everything merge() is associative/commutative over
    (excludes `last`, which is documented as order-dependent); sums compare
    approximately — float addition itself only associates approximately."""
    assert a._counts == b._counts
    assert a.count == b.count
    assert a.max == b.max
    assert a.sum == pytest.approx(b.sum, rel=1e-9, abs=1e-9)


@settings(max_examples=120, deadline=None)
@given(_SAMPLES, _SAMPLES)
def test_merge_commutes(xs, ys):
    ab = _hist(xs).merge(_hist(ys))
    ba = _hist(ys).merge(_hist(xs))
    _assert_same_mergeable_state(ab, ba)


@settings(max_examples=120, deadline=None)
@given(_SAMPLES, _SAMPLES, _SAMPLES)
def test_merge_associates(xs, ys, zs):
    left = _hist(xs).merge(_hist(ys)).merge(_hist(zs))
    right = _hist(xs).merge(_hist(ys).merge(_hist(zs)))
    _assert_same_mergeable_state(left, right)


@settings(max_examples=120, deadline=None)
@given(_SAMPLES.filter(bool), st.floats(min_value=0.01, max_value=1.0))
def test_quantile_rank_bounds(samples, q):
    hist = _hist(samples)
    ordered = sorted(samples)
    rank = min(len(ordered), max(1, math.ceil(q * len(ordered))))
    true_q = ordered[rank - 1]
    got = hist.quantile(q)
    # Never below the true order statistic; never more than one bucket above
    # it (the first bucket's upper bound floors the error for tiny samples).
    assert got >= true_q
    assert got <= max(true_q * GROWTH, FIRST_UPPER_MS) * (1 + 1e-12)
    assert got <= hist.max or hist.count == 0


@settings(max_examples=120, deadline=None)
@given(_SAMPLES, _SAMPLES)
def test_count_and_sum_conserved_through_merge_and_round_trip(xs, ys):
    merged = _hist(xs).merge(_hist(ys))
    assert merged.count == len(xs) + len(ys)
    assert merged.sum == pytest.approx(sum(xs) + sum(ys), rel=1e-9, abs=1e-9)
    back = LogHistogram.from_summary(merged.summary())
    assert back.count == merged.count
    assert sum(back._counts) == merged.count  # every sample lands in a bucket
    assert back.max == merged.max


@settings(max_examples=60, deadline=None)
@given(_SAMPLES.filter(bool))
def test_quantiles_are_monotone_in_q(samples):
    hist = _hist(samples)
    values = [hist.quantile(q) for q in (0.1, 0.5, 0.9, 0.99, 1.0)]
    assert values == sorted(values)
