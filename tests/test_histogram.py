"""Bounded log-bucketed histogram (utils/histogram.py) and the Metrics
registry built on it: the memory-boundedness acceptance claim (1M samples ->
O(buckets) snapshot), bucket-schedule edges, quantile semantics, merge, the
summary/from_summary round trip, and the injected-clock Metrics surface.
Property-based depth (merge associativity/commutativity, quantile rank
bounds, conservation) lives in tests/test_histogram_properties.py.
"""

import json

import pytest

from rapid_tpu.utils.histogram import (
    FIRST_UPPER_MS,
    GROWTH,
    NUM_BUCKETS,
    UPPER_BOUNDS_MS,
    LogHistogram,
    bucket_index,
    cumulative_from_summary,
)
from rapid_tpu.utils.metrics import Metrics


def test_bucket_schedule_is_fixed_and_monotone():
    assert len(UPPER_BOUNDS_MS) == NUM_BUCKETS
    assert UPPER_BOUNDS_MS[0] == FIRST_UPPER_MS
    for lo, hi in zip(UPPER_BOUNDS_MS, UPPER_BOUNDS_MS[1:]):
        assert hi == pytest.approx(lo * GROWTH)


def test_bucket_index_edges():
    assert bucket_index(-1.0) == 0
    assert bucket_index(0.0) == 0
    assert bucket_index(FIRST_UPPER_MS) == 0  # upper bounds are inclusive
    assert bucket_index(FIRST_UPPER_MS * 1.0001) == 1
    for i in (0, 7, NUM_BUCKETS - 1):
        assert bucket_index(UPPER_BOUNDS_MS[i]) == i
    assert bucket_index(UPPER_BOUNDS_MS[-1] * 2) == NUM_BUCKETS  # overflow


def test_quantiles_track_samples_within_one_bucket():
    hist = LogHistogram()
    samples = [1.0, 2.0, 3.0, 4.0, 100.0]
    for s in samples:
        hist.observe(s)
    assert hist.count == 5
    assert hist.sum == pytest.approx(sum(samples))
    assert hist.max == 100.0
    assert hist.last == 100.0
    # Within GROWTH of the true order statistic, never below it.
    assert 3.0 <= hist.quantile(0.5) <= 3.0 * GROWTH
    assert hist.quantile(0.99) == 100.0  # clamped to the exact max
    assert hist.quantile(1.0) == 100.0
    assert LogHistogram().quantile(0.5) == 0.0


def test_merge_adds_counts_and_keeps_max():
    a, b = LogHistogram(), LogHistogram()
    for v in (1.0, 2.0):
        a.observe(v)
    for v in (3.0, 500.0):
        b.observe(v)
    a.merge(b)
    assert a.count == 4
    assert a.sum == pytest.approx(506.0)
    assert a.max == 500.0
    merged = LogHistogram.merged([LogHistogram(), a, LogHistogram()])
    assert merged.count == 4 and merged.max == 500.0


def test_summary_round_trips_through_json():
    hist = LogHistogram()
    for v in (0.2, 5.0, 5.0, 70.0):
        hist.observe(v)
    summary = json.loads(json.dumps(hist.summary()))
    back = LogHistogram.from_summary(summary)
    assert back.count == hist.count
    assert back.sum == pytest.approx(hist.sum)
    assert back.max == hist.max
    for q in (0.5, 0.9, 0.99):
        assert back.quantile(q) == hist.quantile(q)


def test_cumulative_buckets_end_at_total_and_inf():
    hist = LogHistogram()
    for v in (1.0, 2.0, 2.0):
        hist.observe(v)
    buckets = hist.cumulative_buckets()
    assert buckets[-1] == ("+Inf", 3)
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)  # cumulative
    assert cumulative_from_summary({"count": 1}) is None  # legacy dict


def test_metrics_snapshot_memory_is_bounded_at_one_million_samples():
    """The acceptance claim: recording 1M samples into ONE timer yields an
    O(buckets) snapshot — bounded bucket count and a small serialized form,
    where the old per-name List[float] held 1M floats."""
    metrics = Metrics()
    for i in range(1_000_000):
        metrics.record_ms("convergence", float(i % 1000))
    summary = metrics.summary()["convergence_ms"]
    assert summary["count"] == 1_000_000
    assert len(summary["buckets"]) <= NUM_BUCKETS + 1
    assert len(json.dumps(summary)) < 4096
    assert summary["max"] == 999.0
    assert 500.0 <= summary["p50"] <= 500.0 * GROWTH


def test_metrics_uses_injected_clock_for_timer_and_mark():
    now = [1000.0]
    metrics = Metrics(now_ms=lambda: now[0])
    with metrics.timer("step"):
        now[0] += 250.0
    assert metrics.summary()["step_ms"]["last"] == 250.0
    metrics.mark("epoch")
    now[0] += 40.0
    assert metrics.elapsed_since_ms("epoch") == 40.0
    assert metrics.has_mark("epoch")
    metrics.clear_mark("epoch")
    assert not metrics.has_mark("epoch")
    assert metrics.elapsed_since_ms("epoch") == 0.0


def test_metrics_phase_family_summary_shape():
    metrics = Metrics(now_ms=lambda: 0.0)
    metrics.record_ms("view_change_phase", 5.0, phase="detection")
    metrics.record_ms("view_change_phase", 9.0, phase="agreement/fast")
    summary = metrics.summary()["view_change_phase_ms"]
    assert set(summary) == {"detection", "agreement/fast"}
    assert summary["detection"]["count"] == 1
    # Family entries are phase->histogram dicts (no top-level "count"):
    # that shape difference is how the exposition layer tells a labeled
    # family from a plain timer.
    assert "count" not in summary
