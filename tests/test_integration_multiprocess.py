"""Tier-4 integration: real OS processes running the standalone agent over
TCP (the reference's RapidNodeRunner / RapidNodeRunnerTest:
integration-tests spawn `java -jar standalone-agent.jar` subprocesses and
assert liveness; here: `python examples/standalone_agent.py`)."""

import os
import re
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
AGENT = REPO / "examples" / "standalone_agent.py"
def free_ports(count: int):
    """Kernel-assigned ports for agent subprocesses: fixed ranges collide
    with whatever else runs on the host (a concurrent suite run flaked
    exactly that way). Reserve-then-release via the shared helper."""
    from helpers import free_endpoints

    return [ep.port for ep in free_endpoints(count)]


class AgentRunner:
    """Spawn/kill agent subprocesses (RapidNodeRunner.java:63-122 semantics:
    forcible kill on teardown, log-scraped assertions)."""

    def __init__(self, tmp_path: Path):
        self.tmp_path = tmp_path
        self.procs = {}

    def spawn(self, port: int, seed_port: int, role: str = "", extra=()) -> None:
        log = open(self.tmp_path / f"agent-{port}.log", "wb")
        env = dict(os.environ)
        env["PYTHONPATH"] = f"{REPO}:{env.get('PYTHONPATH', '')}"
        env["JAX_PLATFORMS"] = "cpu"  # agents don't need the TPU tunnel
        args = [
            sys.executable, str(AGENT),
            "--listen-address", f"127.0.0.1:{port}",
            "--seed-address", f"127.0.0.1:{seed_port}",
            "--report-interval", "0.25",
        ]
        if role:
            args += ["--role", role]
        args += list(extra)
        self.procs[port] = subprocess.Popen(
            args, stdout=log, stderr=subprocess.STDOUT, env=env, cwd=str(REPO)
        )

    def kill(self, port: int, sig=signal.SIGKILL) -> None:
        proc = self.procs.pop(port, None)
        if proc is not None:
            proc.send_signal(sig)
            proc.wait(timeout=10)

    def teardown(self) -> None:
        for port in list(self.procs):
            self.kill(port)

    def latest_membership_size(self, port: int):
        log_path = self.tmp_path / f"agent-{port}.log"
        if not log_path.exists():
            return None
        sizes = re.findall(rb"membership size: (\d+)", log_path.read_bytes())
        return int(sizes[-1]) if sizes else None

    def wait_for_size(self, ports, size, timeout_s=60.0) -> bool:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if all(self.latest_membership_size(p) == size for p in ports):
                return True
            time.sleep(0.25)
        return False


@pytest.fixture
def runner(tmp_path):
    r = AgentRunner(tmp_path)
    yield r
    r.teardown()


def test_single_agent_starts(runner):
    (port,) = free_ports(1)
    runner.spawn(port, port)
    assert runner.wait_for_size([port], 1, timeout_s=30)
    assert runner.procs[port].poll() is None  # still alive


def test_five_agents_converge_and_survive_a_kill(runner):
    # Ports are allocated immediately before their spawns: reserving the
    # whole set up-front would widen the reserve-then-release race (the
    # running seed's outbound ephemeral connections draw from the same
    # kernel range the reserved ports were released back into).
    (seed_port,) = free_ports(1)
    runner.spawn(seed_port, seed_port)
    assert runner.wait_for_size([seed_port], 1, timeout_s=30)
    ports = [seed_port] + free_ports(4)
    for port in ports[1:]:
        runner.spawn(port, ports[0])
    assert runner.wait_for_size(ports, 5, timeout_s=90)

    # Hard-kill one member; survivors converge to 4 via failure detection
    # (PingPong FD: ~10 intervals) + consensus.
    victim = ports[2]
    runner.kill(victim)
    survivors = [p for p in ports if p != victim]
    assert runner.wait_for_size(survivors, 4, timeout_s=120)


@pytest.mark.slow
def test_ten_agents_converge(runner):
    # RapidNodeRunnerTest's 10-JVM bring-up (RapidNodeRunnerTest.java:28-57):
    # ten real OS processes join through one seed and all converge on the
    # same membership size.
    # Rides the unfiltered check.sh pass (~26 s wall of real-process
    # bring-up); the five-agent converge+kill and windowed-FD kill tests
    # keep the multiprocess path in tier-1.
    (seed_port,) = free_ports(1)
    runner.spawn(seed_port, seed_port)
    assert runner.wait_for_size([seed_port], 1, timeout_s=30)
    ports = [seed_port] + free_ports(9)
    for port in ports[1:]:
        runner.spawn(port, ports[0])
    assert runner.wait_for_size(ports, 10, timeout_s=90)
    for port in ports:
        assert runner.procs[port].poll() is None  # every agent still alive


def test_windowed_fd_agents_detect_kill(runner):
    # Real processes on the PAPER's failure-detection policy (--fd windowed):
    # a SIGKILLed member is detected and evicted by the survivors.
    (seed_port,) = free_ports(1)
    runner.spawn(seed_port, seed_port, extra=["--fd", "windowed"])
    assert runner.wait_for_size([seed_port], 1, timeout_s=30)
    ports = [seed_port] + free_ports(2)
    for port in ports[1:]:
        runner.spawn(port, ports[0], extra=["--fd", "windowed"])
    assert runner.wait_for_size(ports, 3, timeout_s=60)
    runner.kill(ports[2], signal.SIGKILL)
    assert runner.wait_for_size(ports[:2], 2, timeout_s=90)
