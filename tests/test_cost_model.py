"""Unit gate for the scaling-law cost model (tools/analysis/cost_model).

The fitter must put synthetic series in the right class, REFUSE noisy or
under-determined ladders rather than guess, and the lock machinery must
round-trip byte-identically, block superlinear freezes by name, and keep
the committed ``cost.lock.json`` consistent with the live registry. The
expensive real-ladder compiles are exercised by the whole-tree sweep in
``test_lint.py`` — everything here runs on synthetic tables so the unit
tier stays cheap.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import pytest  # noqa: E402

import staticcheck  # noqa: E402
from analysis import cost_model, device_program  # noqa: E402


def _n_points(values, k=4):
    """[(n, value), ...] -> the ((n, k), value) shape fit_scaling takes."""
    return [((n, k), v) for n, v in values]


# ---------------------------------------------------------------------------
# The fitter: synthetic series land in the right class
# ---------------------------------------------------------------------------


def test_fit_constant_series_is_o1():
    fit = cost_model.fit_scaling(
        _n_points([(64, 10.0), (128, 10.0), (256, 10.0), (512, 10.0)]), 0.02
    )
    assert fit["class"] == "O(1)" and fit["coeff"] == pytest.approx(10.0)


def test_fit_all_zero_series_is_o1_with_zero_coeff():
    fit = cost_model.fit_scaling(
        _n_points([(64, 0.0), (128, 0.0), (256, 0.0), (512, 0.0)]), 0.02
    )
    assert fit["class"] == "O(1)" and fit["coeff"] == 0.0
    assert fit["residual"] == 0.0


def test_fit_logarithmic_series_is_olog():
    fit = cost_model.fit_scaling(
        _n_points([(64, 12.0), (128, 14.0), (256, 16.0), (512, 18.0)]), 0.02
    )
    assert fit["class"] == "O(log N)"
    assert fit["coeff"] == pytest.approx(2.0)


def test_fit_affine_series_is_on_not_olog():
    fit = cost_model.fit_scaling(
        _n_points([(64, 300.0), (128, 492.0), (256, 876.0), (512, 1644.0)]),
        0.02,
    )
    assert fit["class"] == "O(N)" and fit["coeff"] == pytest.approx(3.0)


def test_fit_nk_mixture_needs_the_k_axis():
    # The real step signature: 108 + 253*N + 38*N*K. With K varying the
    # mixture is identified exactly; collapsed to one K it must fall back
    # to O(N) (classifying O(N*K) off an N-only ladder would be a guess).
    mix = lambda n, k: 108.0 + 253.0 * n + 38.0 * n * k  # noqa: E731
    varied = [((n, 4), mix(n, 4)) for n in (64, 128, 256, 512)]
    varied += [((256, k), mix(256, k)) for k in (2, 8)]
    fit = cost_model.fit_scaling(varied, 0.02)
    assert fit["class"] == "O(N*K)" and fit["coeff"] == pytest.approx(38.0)

    fixed_k = cost_model.fit_scaling(
        [((n, 4), mix(n, 4)) for n in (64, 128, 256, 512)], 0.02
    )
    assert fixed_k["class"] == "O(N)"


def test_fit_quadratic_series_is_on2():
    fit = cost_model.fit_scaling(
        _n_points([(8, 32.0), (16, 128.0), (32, 512.0), (64, 2048.0)]), 0.02
    )
    assert fit["class"] == "O(N^2)"
    assert fit["coeff"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# Refusals: never guess
# ---------------------------------------------------------------------------


def test_fit_refuses_short_ladder():
    fit = cost_model.fit_scaling(_n_points([(64, 1.0), (512, 8.0)]), 0.02)
    assert "error" in fit and "ladder" in fit["error"]


def test_fit_refuses_noisy_series_instead_of_guessing():
    fit = cost_model.fit_scaling(
        _n_points([(64, 100.0), (128, 900.0), (256, 150.0), (512, 4000.0)]),
        0.02,
    )
    assert "error" in fit
    assert "residual" in fit["error"]


def test_fit_refuses_dtype_step_series():
    # Bytes-per-element doubling halfway up the ladder is a policy step
    # function, not a scaling law — exactly the compact-layout lesson the
    # real ladder hit (min_index_dtype widens at n=128).
    fit = cost_model.fit_scaling(
        _n_points([(8, 8.0), (16, 16.0), (32, 64.0), (64, 128.0)]), 0.02
    )
    assert "error" in fit


def test_fit_refuses_exactly_determined_quadratic():
    # 3 points cannot justify the 3-basis O(N^2) model (points must
    # strictly exceed bases) — an exactly-determined system fits anything.
    fit = cost_model.fit_scaling(
        _n_points([(8, 70.0), (16, 270.0), (32, 1060.0)]), 0.02
    )
    assert "error" in fit


# ---------------------------------------------------------------------------
# Lock construction, refusal gates, byte-identical regen
# ---------------------------------------------------------------------------


def _synthetic_table(arg_values):
    """A one-entrypoint collect_ladder() table with the given
    argument_bytes series (and a constant transfer_ops fact)."""
    return {
        "step": [
            {
                "key": f"n{n}_k4",
                "n_eff": n,
                "k": 4,
                "facts": {"argument_bytes": v, "transfer_ops": 0.0},
            }
            for n, v in arg_values
        ]
    }


_LINEAR = [(64, 16300.0), (128, 32492.0), (256, 64876.0), (512, 129644.0)]
_QUADRATIC = [(64, 16384.0), (128, 65536.0), (256, 262144.0),
              (512, 1048576.0)]


def _patch_collectors(monkeypatch, table, tmp_path):
    monkeypatch.setattr(cost_model, "collect_ladder",
                        lambda *a, **kw: table)
    monkeypatch.setattr(
        cost_model, "collect_quiescent_cost",
        lambda *a, **kw: {
            "entrypoint": "sharded_step",
            "collective_payload_bytes": 53218,
            "hot_loop_payload_bytes": 0,
            "flops": 161789.0,
        },
    )
    monkeypatch.setattr(device_program, "compaction_differential_ok",
                        lambda: None)
    monkeypatch.setattr(device_program, "trace_differential_ok",
                        lambda: None)
    target = tmp_path / "cost.lock.json"
    monkeypatch.setattr(cost_model, "COST_LOCK_REL", str(target))
    return target


def test_update_cost_lock_round_trips_byte_identical(monkeypatch, tmp_path):
    target = _patch_collectors(
        monkeypatch, _synthetic_table(_LINEAR), tmp_path
    )
    findings, path = cost_model.update_cost_lock()
    assert findings == [] and path == target
    first = target.read_bytes()
    locked = json.loads(first)
    assert locked["entrypoints"]["step"]["facts"]["argument_bytes"][
        "class"] == "O(N)"
    assert locked["quiescent_round_cost"]["collective_payload_bytes"] == 53218

    findings, _path = cost_model.update_cost_lock()
    assert findings == []
    assert target.read_bytes() == first

    # ... and the gate sweeps clean against what the generator just wrote.
    fits, refusals = cost_model.fit_ladder(_synthetic_table(_LINEAR))
    assert refusals == []
    drift = cost_model.compare_cost_lock(
        fits, cost_model.collect_quiescent_cost(), locked, str(target)
    )
    assert drift == [], drift


def test_update_cost_lock_refuses_superlinear_by_name(monkeypatch, tmp_path):
    target = _patch_collectors(
        monkeypatch, _synthetic_table(_QUADRATIC), tmp_path
    )
    findings, path = cost_model.update_cost_lock()
    assert path is None and not target.exists()
    checks = [f.check for f in findings]
    assert checks == ["cost-superlinear"]
    message = findings[0].message
    assert "step" in message and "argument_bytes" in message
    assert "O(N^2)" in message and "O(N*K)" in message


def test_update_cost_lock_refuses_unexplained(monkeypatch, tmp_path):
    stepped = [(8, 8.0), (16, 16.0), (32, 64.0), (64, 128.0)]
    target = _patch_collectors(
        monkeypatch, _synthetic_table(stepped), tmp_path
    )
    findings, path = cost_model.update_cost_lock()
    assert path is None and not target.exists()
    assert [f.check for f in findings] == ["cost-unexplained"]
    assert "step" in findings[0].message
    assert "argument_bytes" in findings[0].message


def test_injected_regression_fails_gate_with_old_and_new_class(
    monkeypatch, tmp_path
):
    # Freeze the linear world, then swap in a quadratic artifact under a
    # raised ceiling: the drift report must name the entrypoint, the fact,
    # and both classes.
    target = _patch_collectors(
        monkeypatch, _synthetic_table(_LINEAR), tmp_path
    )
    _findings, _path = cost_model.update_cost_lock()
    locked = json.loads(target.read_text())

    monkeypatch.setitem(cost_model.COST_CEILINGS, "step", "O(N^2)")
    fits, refusals = cost_model.fit_ladder(_synthetic_table(_QUADRATIC))
    assert refusals == []
    findings = cost_model.compare_cost_lock(
        fits, cost_model.collect_quiescent_cost(), locked, str(target)
    )
    regressions = [f for f in findings
                   if f.check == "cost-scaling-regression"]
    assert len(regressions) == 1
    message = regressions[0].message
    assert "step" in message and "argument_bytes" in message
    assert "O(N)" in message and "O(N^2)" in message and "WORSENED" in message


def test_quiescent_drift_is_named(monkeypatch, tmp_path):
    target = _patch_collectors(
        monkeypatch, _synthetic_table(_LINEAR), tmp_path
    )
    _findings, _path = cost_model.update_cost_lock()
    locked = json.loads(target.read_text())

    findings = cost_model.compare_quiescent(
        dict(locked["quiescent_round_cost"], collective_payload_bytes=99999),
        locked["quiescent_round_cost"], str(target),
    )
    assert [f.check for f in findings] == ["cost-quiescent"]
    assert "collective_payload_bytes" in findings[0].message

    # FLOPs wobble within 10% is tolerated; beyond it is drift.
    near = dict(locked["quiescent_round_cost"],
                flops=locked["quiescent_round_cost"]["flops"] * 1.05)
    assert cost_model.compare_quiescent(
        near, locked["quiescent_round_cost"], str(target)) == []
    far = dict(locked["quiescent_round_cost"],
               flops=locked["quiescent_round_cost"]["flops"] * 1.5)
    drifted = cost_model.compare_quiescent(
        far, locked["quiescent_round_cost"], str(target))
    assert [f.check for f in drifted] == ["cost-quiescent"]


# ---------------------------------------------------------------------------
# The committed lock: acceptance-criteria pins (no compiles — pure reads)
# ---------------------------------------------------------------------------


def test_committed_lock_covers_every_registered_entrypoint():
    locked = json.loads(
        (staticcheck.core.REPO / cost_model.COST_LOCK_REL).read_text()
    )
    assert set(locked["entrypoints"]) == set(cost_model.COST_REGISTRY)
    for name, entry in locked["entrypoints"].items():
        facts = entry["facts"]
        for fact in ("collective_payload_bytes", "argument_bytes",
                     "temp_bytes"):
            assert fact in facts, (name, fact)
        ceiling = entry["ceiling"]
        for fact, fit in facts.items():
            assert (
                cost_model.CLASS_RANK[fit["class"]]
                <= cost_model.CLASS_RANK[ceiling]
            ), (name, fact, fit["class"])


def test_committed_lock_freezes_the_quiescent_round_cost():
    locked = json.loads(
        (staticcheck.core.REPO / cost_model.COST_LOCK_REL).read_text()
    )
    quiescent = locked["quiescent_round_cost"]
    assert quiescent["entrypoint"] == "sharded_step"
    assert quiescent["collective_payload_bytes"] > 0
    assert quiescent["hot_loop_payload_bytes"] == 0
    assert locked["ladder_config"] == cost_model._ladder_config()


def test_cost_checks_are_registered_and_selectable():
    new = {"cost-unexplained", "cost-scaling-regression", "cost-superlinear",
           "cost-quiescent", "cost-lock-drift"}
    assert new <= set(staticcheck.ALL_CHECK_NAMES)
    assert any(name == "cost_model" for name, _ in staticcheck.FAMILIES)
