"""DeviceVoteTally: quorum behavior equivalent to the host hash-map tally,
plus FastPaxos running with the device tally plugged in."""

import random

import pytest

from rapid_tpu.protocol.device_vote_tally import DeviceVoteTally
from rapid_tpu.protocol.fast_paxos import FastPaxos, fast_paxos_quorum
from rapid_tpu.types import Endpoint, FastRoundPhase2bMessage
from rapid_tpu.utils.clock import ManualClock


def ep(i: int) -> Endpoint:
    return Endpoint("127.0.0.1", i)


@pytest.mark.parametrize("n", [5, 6, 10, 20, 102])
def test_decides_exactly_at_quorum(n):
    tally = DeviceVoteTally(n)
    quorum = fast_paxos_quorum(n)
    proposal = (ep(9999), ep(8888))
    for i in range(quorum - 1):
        assert tally.add_vote(ep(100 + i), proposal) is None
    assert tally.add_vote(ep(100 + quorum - 1), proposal) == proposal


def test_conflicting_votes_block_and_dedup():
    n = 10
    tally = DeviceVoteTally(n)
    quorum = fast_paxos_quorum(n)  # 8
    va, vb = (ep(1),), (ep(2),)
    # 3 conflicting votes: only 7 identical votes remain possible.
    for i in range(3):
        assert tally.add_vote(ep(200 + i), vb) is None
    for i in range(n - 3):
        assert tally.add_vote(ep(300 + i), va) is None
    # Duplicate senders never double-count.
    assert tally.add_vote(ep(300), va) is None


def test_fast_paxos_with_device_tally():
    n = 8
    decided = []
    fp = FastPaxos(
        my_addr=ep(0),
        configuration_id=1,
        membership_size=n,
        broadcast_fn=lambda r: None,
        send_fn=lambda d, r: None,
        on_decide=lambda hosts: decided.append(tuple(hosts)),
        clock=ManualClock(),
        rng=random.Random(0),
        vote_tally=DeviceVoteTally(n),
    )
    proposal = (ep(7777),)
    quorum = fast_paxos_quorum(n)
    for i in range(quorum - 1):
        fp.handle_message(
            FastRoundPhase2bMessage(sender=ep(100 + i), configuration_id=1, endpoints=proposal)
        )
    assert decided == []
    fp.handle_message(
        FastRoundPhase2bMessage(sender=ep(100 + quorum - 1), configuration_id=1,
                                endpoints=proposal)
    )
    assert decided == [proposal]
    # Further votes after the decision are ignored (decided latch).
    fp.handle_message(
        FastRoundPhase2bMessage(sender=ep(999), configuration_id=1, endpoints=proposal)
    )
    assert decided == [proposal]


def test_cluster_with_device_tally_and_detector():
    # The full north-star bridge: host nodes whose cut detection AND vote
    # tallies both run as device-kernel calls.
    import asyncio

    from rapid_tpu.messaging.inprocess import InProcessNetwork
    from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
    from rapid_tpu.protocol.cluster import Cluster
    from rapid_tpu.protocol.device_cut_detector import DeviceCutDetector
    from rapid_tpu.settings import Settings

    async def scenario():
        settings = Settings()
        settings.batching_window_ms = 20
        settings.failure_detector_interval_ms = 50
        network = InProcessNetwork()
        fd = StaticFailureDetectorFactory()

        def ep_(i):
            return Endpoint("127.0.0.1", 43200 + i)

        def detector_factory(k, h, l):
            return DeviceCutDetector(k, h, l, max_slots=64)

        def tally_factory(membership_size):
            return DeviceVoteTally(membership_size)

        clusters = [
            await Cluster.start(ep_(0), settings=settings, network=network, fd_factory=fd,
                                rng=random.Random(0), cut_detector_factory=detector_factory,
                                vote_tally_factory=tally_factory)
        ]
        for i in range(1, 5):
            clusters.append(
                await Cluster.join(ep_(0), ep_(i), settings=settings, network=network,
                                   fd_factory=fd, rng=random.Random(i),
                                   cut_detector_factory=detector_factory,
                                   vote_tally_factory=tally_factory)
            )

        async def converged(cs, size):
            for _ in range(600):
                if all(c.membership_size == size for c in cs) and (
                    len({tuple(c.membership) for c in cs}) == 1
                ):
                    return True
                await asyncio.sleep(0.02)
            return False

        assert await converged(clusters, 5)
        victim = clusters[3]
        network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([victim.listen_address])
        survivors = [c for c in clusters if c is not victim]
        assert await converged(survivors, 4)
        for c in clusters:
            await c.shutdown()

    asyncio.run(asyncio.wait_for(scenario(), timeout=60))
