"""Direct unit tests for the in-process transport's fault seams.

The chaos subsystem compiles schedules onto ``blackholed``,
``blackholed_links``, ``ServerDropFirstN``, and the ``shaper`` hook — until
now those seams were only exercised incidentally inside whole-cluster chaos
tests. These tests pin their exact semantics at the transport level:
directionality of link blackholes, heal behavior, interceptor interaction,
and the shaper's three message fates (drop / simulated-time delay /
server-side double delivery)."""

import asyncio
import functools
import random

import pytest

from rapid_tpu.messaging.inprocess import (
    InProcessClient,
    InProcessNetwork,
    InProcessServer,
    ServerDropFirstN,
)
from rapid_tpu.sim.faults import LinkShaper
from rapid_tpu.types import (
    Endpoint,
    NodeStatus,
    ProbeMessage,
    ProbeResponse,
)
from rapid_tpu.utils.clock import ManualClock

A = Endpoint("10.99.0.1", 1)
B = Endpoint("10.99.0.2", 2)


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=30)

        asyncio.run(with_timeout())

    return wrapper


async def _pair(network):
    """Servers at A and B (service-less: probes answered BOOTSTRAPPING) and
    clients at both, attached to one network."""
    servers = {}
    clients = {}
    for endpoint in (A, B):
        server = InProcessServer(network, endpoint)
        await server.start()
        servers[endpoint] = server
        clients[endpoint] = InProcessClient(network, endpoint)
    return servers, clients


@async_test
async def test_blackholed_links_are_directional():
    network = InProcessNetwork()
    _, clients = await _pair(network)
    network.blackholed_links.add((A, B))

    # A -> B drops ...
    assert await clients[A].send_best_effort(B, ProbeMessage(sender=A)) is None
    # ... while B -> A delivers on the very same link pair.
    response = await clients[B].send_best_effort(A, ProbeMessage(sender=B))
    assert isinstance(response, ProbeResponse)
    assert response.status == NodeStatus.BOOTSTRAPPING


@async_test
async def test_blackhole_then_heal_restores_delivery():
    network = InProcessNetwork()
    _, clients = await _pair(network)

    network.blackholed.add(B)
    assert await clients[A].send_best_effort(B, ProbeMessage(sender=A)) is None
    # A node-level blackhole also silences the victim's EGRESS (a crashed
    # process neither answers nor sends).
    assert await clients[B].send_best_effort(A, ProbeMessage(sender=B)) is None

    network.blackholed.discard(B)
    assert await clients[A].send_best_effort(B, ProbeMessage(sender=A)) is not None

    network.blackholed_links.add((A, B))
    assert await clients[A].send_best_effort(B, ProbeMessage(sender=A)) is None
    network.blackholed_links.discard((A, B))
    assert await clients[A].send_best_effort(B, ProbeMessage(sender=A)) is not None


@async_test
async def test_drop_first_n_interacts_with_link_faults():
    network = InProcessNetwork()
    servers, clients = await _pair(network)
    servers[B].drop_interceptors.append(ServerDropFirstN(ProbeMessage, 2))

    # While the link is blackholed the message never REACHES the server, so
    # the interceptor's drop budget must not be consumed.
    network.blackholed_links.add((A, B))
    assert await clients[A].send_best_effort(B, ProbeMessage(sender=A)) is None
    network.blackholed_links.discard((A, B))

    # The budget is intact: exactly the next two server-side deliveries drop.
    assert await clients[A].send_best_effort(B, ProbeMessage(sender=A)) is None
    assert await clients[A].send_best_effort(B, ProbeMessage(sender=A)) is None
    assert await clients[A].send_best_effort(B, ProbeMessage(sender=A)) is not None


@async_test
async def test_shaper_drop_and_duplicate_fates():
    network = InProcessNetwork()
    servers, clients = await _pair(network)
    shaper = LinkShaper(random.Random(0), ManualClock())
    network.shaper = shaper

    shaper.loss_permille = 1000  # every message dropped
    assert await clients[A].send_best_effort(B, ProbeMessage(sender=A)) is None
    assert shaper.dropped == 1

    shaper.loss_permille = 0
    shaper.dup_permille = 1000  # every message delivered twice
    servers[B].drop_interceptors.append(ServerDropFirstN(ProbeMessage, 1))
    # One logical send: the duplicate consumes the interceptor's single drop
    # at the server, and the caller still gets the second copy's response —
    # receiver-side dedup is what duplication exercises.
    response = await clients[A].send_best_effort(B, ProbeMessage(sender=A))
    assert isinstance(response, ProbeResponse)
    assert shaper.duplicated == 1


@async_test
async def test_shaper_delay_holds_for_simulated_time():
    network = InProcessNetwork()
    clock = ManualClock()
    _, clients = await _pair(network)
    shaper = LinkShaper(random.Random(0), clock)
    network.shaper = shaper
    shaper.delay_min_ms = 100.0
    shaper.delay_max_ms = 100.0

    task = asyncio.ensure_future(
        clients[A].send_best_effort(B, ProbeMessage(sender=A))
    )
    for _ in range(20):
        await asyncio.sleep(0)
    assert not task.done()  # held: simulated time has not advanced
    clock.advance_ms(101)
    for _ in range(20):
        await asyncio.sleep(0)
    assert task.done()
    assert isinstance(task.result(), ProbeResponse)
    assert shaper.delayed == 1


@async_test
async def test_shaper_none_is_the_default_clean_path():
    network = InProcessNetwork()
    _, clients = await _pair(network)
    assert network.shaper is None
    assert isinstance(
        await clients[A].send_best_effort(B, ProbeMessage(sender=A)),
        ProbeResponse,
    )


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
