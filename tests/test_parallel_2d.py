"""2-D ('cohort', 'nodes') mesh parity: the cohort-meshed engine must be
bit-identical to the single-device engine.

A pinned grid of churn scenarios drives the 2-D sharded ``step`` (2x4 over
the forced 8-device CPU mesh) and the single-device path side by side: the
cut sequences, configuration ids, and decision rounds must match exactly,
and the whole-wave entrypoint must commit the same multi-cut resolution in
one dispatch. The cut-sequence comparison reuses the sim oracle battery's
refinement checker (``sim/oracles.cuts_refine`` — the same relation the
host<->device differential oracle uses): bit-identical engines must refine
each other in BOTH directions, which degenerates to equality.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.parallel.mesh import (
    COHORT_AXIS,
    NODE_AXIS,
    ShardingShapeError,
    make_mesh,
    make_sharded_step,
    make_sharded_wave,
    pad_to_multiple,
    shard_faults,
    shard_state,
    state_shardings,
)
from rapid_tpu.sim.oracles import cuts_refine

MESH_SHAPE = (2, 4)  # ('cohort', 'nodes') over the 8 virtual CPU devices


def make_mesh_2d():
    return make_mesh(jax.devices()[:8], shape=MESH_SHAPE)


#: Pinned scenario grid: (name, builder). Shapes divide the 2x4 mesh
#: (n % 4 == 0, cohorts % 2 == 0). Each builder returns (vc, target, the
#: max steps to drive).
def _crash_only():
    vc = VirtualCluster.create(248, n_slots=256, fd_threshold=2, seed=0, cohorts=8)
    vc.assign_cohorts_roundrobin()
    vc.crash([3, 77, 130])
    return vc, 245, 12


def _join_wave():
    vc = VirtualCluster.create(
        192, n_slots=256, fd_threshold=2, seed=1, delivery_spread=1, cohorts=4
    )
    vc.assign_cohorts_roundrobin()
    vc.inject_join_wave(list(range(192, 240)))
    return vc, 240, 12


def _staggered_multi_cut():
    vc = VirtualCluster.create(
        60, n_slots=72, cohorts=16, fd_threshold=2, seed=11, delivery_spread=1
    )
    vc.assign_cohorts_roundrobin()
    vc.crash([7, 31])
    # Staggered detection pushes the crash cut behind the join cut: the
    # scenario genuinely resolves through >= 2 view changes.
    vc.stagger_fd_counts(np.random.default_rng(5), spread_rounds=8)
    vc.inject_join_wave(list(range(60, 72)))
    return vc, 70, 40


def _leave_and_crash_jittered():
    vc = VirtualCluster.create(
        120, n_slots=128, cohorts=8, fd_threshold=2, seed=3, delivery_spread=2,
        concurrent_coordinators=2,
    )
    vc.assign_cohorts_roundrobin()
    vc.stagger_fd_counts(np.random.default_rng(9), spread_rounds=2)
    vc.initiate_leave([5, 44])
    vc.crash([90])
    return vc, 117, 40


#: The tier-1 half of the grid runs on every test session; the heavier
#: half rides the unfiltered full-suite pass (tools/check.sh) as ``slow``
#: — each scenario costs two engine compiles (single-device + 2-D).
SCENARIOS = {
    "crash_only": _crash_only,
    "staggered_multi_cut": _staggered_multi_cut,
}
SLOW_SCENARIOS = {
    "join_wave": _join_wave,
    "leave_and_crash_jittered": _leave_and_crash_jittered,
}


def _drive(step_fn, state, faults, max_steps):
    """(cuts, config_ids, decision_rounds) of a per-step drive: one cut per
    decided round, labeled (slot, up/down) like the sim oracles' cuts."""
    cuts, config_ids, rounds = [], [], []
    for i in range(max_steps):
        was_alive = np.asarray(state.alive)
        state, events = step_fn(state, faults)
        if bool(events.decided):
            mask = np.asarray(events.winner_mask)
            cuts.append(frozenset(
                (s, "down" if was_alive[s] else "up")
                for s in np.nonzero(mask)[0].tolist()
            ))
            config_ids.append(
                (int(state.config_hi) << 32) | int(state.config_lo)
            )
            rounds.append(i)
    return state, cuts, config_ids, rounds


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_2d_step_parity_against_single_device(name):
    _assert_step_parity(SCENARIOS[name], name)


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(SLOW_SCENARIOS))
def test_2d_step_parity_against_single_device_slow(name):
    _assert_step_parity(SLOW_SCENARIOS[name], name)


def _assert_step_parity(build, name):
    single, target, max_steps = build()

    def single_step(state, faults):
        del state, faults
        events = single.step()
        return single.state, events

    _, cuts_1, ids_1, rounds_1 = _drive(
        single_step, single.state, single.faults, max_steps
    )

    vc, _, _ = build()
    mesh = make_mesh_2d()
    step = make_sharded_step(vc.cfg, mesh)
    state = shard_state(vc.state, mesh)
    faults = shard_faults(vc.faults, mesh)
    state, cuts_2, ids_2, rounds_2 = _drive(step, state, faults, max_steps)

    assert cuts_1, f"{name}: scenario produced no cuts — not a parity case"
    # Bit-identical: same cuts at the same rounds committing the same
    # configuration ids, and the same final state.
    assert rounds_2 == rounds_1
    assert ids_2 == ids_1
    assert cuts_2 == cuts_1
    assert int(state.n_members) == single.membership_size == target
    np.testing.assert_array_equal(np.asarray(state.alive), single.alive_mask)
    # The sim battery's refinement relation as the comparator: identical
    # sequences refine each other in both directions (each cut its own
    # group).
    assert cuts_refine(cuts_2, [[c] for c in cuts_1]) is None
    assert cuts_refine(cuts_1, [[c] for c in cuts_2]) is None


@pytest.mark.slow
def test_2d_wave_parity_multi_cut_single_dispatch():
    """The whole-wave entrypoint on the 2-D mesh: a churn resolving through
    MULTIPLE cohort-meshed view changes in one dispatch matches the
    single-device fused loop exactly — rounds, cuts, per-cut sizes, final
    configuration."""
    single, target, _ = _staggered_multi_cut()
    r1, c1, resolved1, sizes1 = single.run_until_membership(target, min_cuts=1)
    assert resolved1 and c1 >= 2  # genuinely multi-cuts

    vc, _, _ = _staggered_multi_cut()
    mesh = make_mesh_2d()
    wave = make_sharded_wave(vc.cfg, mesh, max_cuts=8)
    state, steps, cuts, resolved, sizes = wave(
        shard_state(vc.state, mesh), shard_faults(vc.faults, mesh),
        jnp.int32(target), jnp.int32(192), jnp.int32(1),
    )
    assert bool(resolved)
    assert (int(steps), int(cuts)) == (r1, c1)
    assert tuple(np.asarray(sizes)[: int(cuts)].tolist()) == sizes1
    assert int(state.n_members) == target == single.membership_size
    np.testing.assert_array_equal(np.asarray(state.alive), single.alive_mask)
    assert int(state.config_hi) == int(single.state.config_hi)
    assert int(state.config_lo) == int(single.state.config_lo)


def test_2d_state_shards_cohort_and_node_axes():
    """[c] lanes shard over 'cohort', [c, n] over both axes, [n] over
    'nodes' — and per-device cohort-state bytes are 1/8 of global (the
    whole point of meshing the cohort axis)."""
    vc, _, _ = _crash_only()
    mesh = make_mesh_2d()
    state = shard_state(vc.state, mesh)
    shardings = state_shardings(mesh)
    assert shardings.seen_down.spec == jax.sharding.PartitionSpec(COHORT_AXIS)
    assert shardings.report_bits.spec == jax.sharding.PartitionSpec(
        COHORT_AXIS, NODE_AXIS
    )
    assert shardings.alive.spec == jax.sharding.PartitionSpec(NODE_AXIS)
    for leaf in (state.report_bits, state.released, state.prop_mask):
        shard = leaf.addressable_shards[0].data
        assert shard.nbytes * 8 == leaf.nbytes, leaf.shape
    for leaf in (state.seen_down, state.announced, state.prop_hi):
        shard = leaf.addressable_shards[0].data
        assert shard.nbytes * 2 == leaf.nbytes, leaf.shape


def test_shard_pytree_names_the_indivisible_leaf():
    """Satellite: a shape that does not divide the mesh axes raises the
    named error (leaf + axis + pad hint), not XLA's opaque one — and
    pad_to_multiple names the fix."""
    vc = VirtualCluster.create(50, n_slots=50, fd_threshold=2, seed=0, cohorts=6)
    mesh = make_mesh_2d()
    with pytest.raises(ShardingShapeError) as err:
        shard_state(vc.state, mesh)
    msg = str(err.value)
    assert "does not divide" in msg and "pad_to_multiple" in msg
    assert pad_to_multiple(50, 4) == 52
    assert pad_to_multiple(52, 4) == 52
    assert pad_to_multiple(0, 8) == 0
    # A padded build shards cleanly.
    vc2 = VirtualCluster.create(
        50, n_slots=pad_to_multiple(50, 4), fd_threshold=2, seed=0,
        cohorts=pad_to_multiple(6, 2),
    )
    shard_state(vc2.state, mesh)


def test_shard_pytree_rejects_wrong_mesh_and_accepts_bare_specs():
    vc, _, _ = _crash_only()
    mesh = make_mesh_2d()
    mesh_1d = make_mesh(jax.devices()[:8])
    from rapid_tpu.parallel.mesh import shard_pytree

    with pytest.raises(ShardingShapeError, match="targets mesh"):
        shard_pytree(vc.state, state_shardings(mesh_1d), mesh=mesh)
    # Bare PartitionSpec leaves resolve against the explicit mesh.
    specs = jax.tree.map(
        lambda sh: sh.spec, state_shardings(mesh),
        is_leaf=lambda x: isinstance(x, jax.sharding.NamedSharding),
    )
    placed = shard_pytree(vc.state, specs, mesh=mesh)
    assert placed.report_bits.sharding.mesh.axis_names == (COHORT_AXIS, NODE_AXIS)
    with pytest.raises(ShardingShapeError, match="explicit mesh"):
        shard_pytree(vc.state, specs)
