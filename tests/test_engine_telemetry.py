"""Device-engine observability tier: compile-event capture, per-dispatch
latency histograms, transfer-byte accounting, device memory stats — surfaced
through the unified ``telemetry_snapshot()`` / ``prometheus_text()`` contract
with the engine metric names pinned as a golden vocabulary (renaming one is
an API break for every scrape config, same rule as the host tier's).
"""

import json
import sys
from pathlib import Path

import pytest

import jax
import jax.numpy as jnp

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import clustertop  # noqa: E402  — tools/clustertop.py, the live dashboard

from rapid_tpu.models.virtual_cluster import VirtualCluster  # noqa: E402
from rapid_tpu.utils import engine_telemetry, exposition  # noqa: E402
from rapid_tpu.utils.histogram import NUM_BUCKETS, LogHistogram  # noqa: E402


def _cluster(n=16, cohorts=2):
    vc = VirtualCluster.create(
        n, k=3, h=3, l=1, cohorts=cohorts, fd_threshold=2, seed=0
    )
    vc.assign_cohorts_roundrobin()
    return vc


#: The engine scrape's complete metric-name vocabulary (host KNOWN_COUNTERS
#: zero-fill + the engine tier). This list is an API — see the host golden
#: list in tests/test_observability.py for the contract.
GOLDEN_ENGINE_METRIC_NAMES = [
    "rapid_alert_batches_redelivered_total",
    "rapid_alert_batches_sent_total",
    "rapid_alerts_enqueued_total",
    "rapid_alerts_received_total",
    "rapid_catch_up_wedged_total",
    "rapid_classic_rounds_started_total",
    "rapid_config_beacons_sent_total",
    "rapid_config_catch_ups_total",
    "rapid_config_pull_unchanged_served_total",
    "rapid_config_sync_unchanged_total",
    "rapid_configuration_id",
    "rapid_decision_missing_joiner_uuid_total",
    "rapid_engine_compile_cache_requests_total",
    "rapid_engine_compile_ms_bucket",
    "rapid_engine_compile_ms_count",
    "rapid_engine_compile_ms_sum",
    "rapid_engine_compiles_total",
    "rapid_engine_convergence_steps_total",
    "rapid_engine_cuts_committed_total",
    "rapid_engine_d2h_bytes_total",
    "rapid_engine_device_bytes_in_use",
    "rapid_engine_device_peak_bytes",
    "rapid_engine_dispatch_ms_bucket",
    "rapid_engine_dispatch_ms_count",
    "rapid_engine_dispatch_ms_sum",
    "rapid_engine_dispatches_total",
    "rapid_engine_h2d_bytes_total",
    "rapid_engine_live_buffer_bytes",
    "rapid_engine_live_buffers",
    "rapid_engine_persistent_cache_hits_total",
    "rapid_engine_persistent_cache_misses_total",
    "rapid_engine_steps_total",
    "rapid_kicked_total",
    "rapid_membership_size",
    "rapid_node_health",
    "rapid_proposals_announced_total",
    "rapid_view_changes_total",
]


def test_engine_prometheus_names_are_golden():
    vc = _cluster()
    vc.crash([3])
    vc.step()
    vc.run_to_decision(max_steps=32)
    vc.sync()
    names = exposition.metric_names(vc.prometheus_text())
    assert names == GOLDEN_ENGINE_METRIC_NAMES


def test_snapshot_engine_section_shape_and_serializable():
    vc = _cluster()
    snap = vc.telemetry_snapshot()
    engine = snap["engine"]
    assert engine["n"] == 16 and engine["cohorts"] == 2
    assert set(engine["compile"]) == {
        "compiles", "compile_ms", "persistent_cache_hits",
        "persistent_cache_misses", "cache_requests",
    }
    assert set(engine["memory"]) == {
        "live_buffers", "live_buffer_bytes",
        "device_bytes_in_use", "device_peak_bytes",
    }
    json.dumps(snap)  # the --metrics-dump / clustertop artifact


def test_compile_events_are_captured():
    # A never-before-seen shape forces a fresh XLA compile; the process-wide
    # collector must see it (count + duration histogram), and CompileDelta
    # must attribute it to the bracketed phase.
    assert engine_telemetry.install() is True
    probe = jax.jit(lambda x: (x * 3 + 1).sum())
    with engine_telemetry.CompileDelta() as delta:
        probe(jnp.arange(173))  # unusual length: not a cached executable
    assert delta.delta["compiles"] >= 1
    assert delta.delta["compile_ms"] > 0
    snap = engine_telemetry.compile_snapshot()
    assert snap["compiles"] >= 1
    assert snap["compile_ms"]["count"] == snap["compiles"]


def test_dispatch_histogram_is_bounded_and_per_entrypoint():
    vc = _cluster()
    vc.crash([3])
    for _ in range(40):
        vc.step()
    vc.run_to_decision(max_steps=8)
    family = vc.metrics.phase_timings["engine_dispatch"]
    # Latencies land in the shared bounded instrument, keyed by entrypoint.
    assert isinstance(family["step"], LogHistogram)
    assert set(family) <= {"step", "run_to_decision", "run_until_membership", "sync"}
    assert family["step"].count == 40
    summary = family["step"].summary()
    # Bounded memory: the summary is O(NUM_BUCKETS) however many dispatches
    # were recorded, and conserves the sample count.
    assert len(summary["buckets"]) <= NUM_BUCKETS + 1
    assert sum(summary["buckets"].values()) == 40
    assert vc.metrics.counters["engine_dispatches"] == 41


def test_convergence_step_and_cut_counters():
    vc = _cluster()
    vc.crash([3])
    rounds, decided, _, _ = vc.run_to_decision(max_steps=32)
    assert decided
    assert vc.metrics.counters["engine_convergence_steps"] == rounds
    assert vc.metrics.counters["engine_cuts_committed"] == 1
    vc2 = _cluster(n=24)
    vc2.crash([1, 2])
    rounds2, cuts2, resolved, _ = vc2.run_until_membership(22, min_cuts=1)
    assert resolved
    assert vc2.metrics.counters["engine_convergence_steps"] == rounds2
    assert vc2.metrics.counters["engine_cuts_committed"] == cuts2


def test_transfer_byte_accounting():
    vc = _cluster()
    # Initial state upload was charged at construction: 4 arrays of (k, n)
    # u32 keys + 2 of (n,) u32 ids + the (n,) alive mask.
    base_h2d = vc.metrics.counters["engine_h2d_bytes"]
    assert base_h2d >= 3 * 16 * 4 * 2 + 16 * 4 * 2 + 16
    vc.crash([1, 2, 3])
    assert vc.metrics.counters["engine_h2d_bytes"] == base_h2d + 3 * 4
    d2h0 = vc.metrics.counters["engine_d2h_bytes"]
    assert vc.membership_size == 16
    assert vc.metrics.counters["engine_d2h_bytes"] == d2h0 + 4
    mask = vc.alive_mask
    assert vc.metrics.counters["engine_d2h_bytes"] == d2h0 + 4 + mask.nbytes


def test_join_wave_accounting_charges_indices_not_device_masks():
    # The join wave's fired-edge mask is DERIVED ON DEVICE (pred >= 0):
    # charging it would require materializing it on host — a full tunnel
    # round trip on the bootstrap timed path. Only the uploaded slot
    # indices (and the [j] admissibility fetch) are real transfers.
    vc = VirtualCluster.create(
        16, n_slots=20, k=3, h=3, l=1, cohorts=2, fd_threshold=2, seed=0
    )
    vc.assign_cohorts_roundrobin()
    h2d0 = vc.metrics.counters["engine_h2d_bytes"]
    d2h0 = vc.metrics.counters["engine_d2h_bytes"]
    vc.inject_join_wave([16, 17])
    assert vc.metrics.counters["engine_h2d_bytes"] == h2d0 + 2 * 4  # idx only
    assert vc.metrics.counters["engine_d2h_bytes"] == d2h0 + 2  # [j] bools
    # A graceful leave's mask IS host-originated (np.ones): charged.
    h2d1 = vc.metrics.counters["engine_h2d_bytes"]
    vc.initiate_leave([2])
    assert vc.metrics.counters["engine_h2d_bytes"] == h2d1 + 4 + 1 * 3  # idx + [1,k] mask


def test_device_memory_snapshot_sees_live_state():
    vc = _cluster()
    vc.sync()
    memory = engine_telemetry.device_memory_snapshot()
    # The engine state alone holds dozens of live device buffers.
    assert memory["live_buffers"] >= 10
    assert memory["live_buffer_bytes"] > 0
    # Allocator stats are platform-optional (None on CPU) but the keys are
    # always present — the scrape shape is stable across platforms.
    assert "device_bytes_in_use" in memory and "device_peak_bytes" in memory


def test_compiled_memory_analysis_of_engine_step():
    from rapid_tpu.models.state import FaultInputs
    from rapid_tpu.models.virtual_cluster import engine_step_nodonate

    vc = _cluster()
    lowered = engine_step_nodonate.lower(
        vc.cfg, vc.state, FaultInputs.none(vc.cfg)
    )
    analysis = engine_telemetry.compiled_memory_analysis(lowered.compile())
    if analysis is not None:  # backend-optional, shape pinned when present
        assert set(analysis) == {
            "argument_bytes", "output_bytes", "temp_bytes",
            "generated_code_bytes",
        }
        assert analysis["argument_bytes"] > 0
    # A backend object without memory_analysis degrades to None, never raises.
    assert engine_telemetry.compiled_memory_analysis(object()) is None


def test_install_is_idempotent():
    first = engine_telemetry.install()
    assert engine_telemetry.install() is first


# ---------------------------------------------------------------------------
# clustertop: the engine pane
# ---------------------------------------------------------------------------


def test_clustertop_renders_engine_pane():
    vc = _cluster()
    vc.crash([3])
    vc.run_to_decision(max_steps=32)
    host_snapshot = {
        "node": "10.0.0.1:9001", "configuration_id": 7, "membership_size": 3,
        "health": "stable", "metrics": {"view_changes": 1},
        "transport": {}, "recorder": None,
    }
    frame = clustertop.render_frame([host_snapshot, vc.telemetry_snapshot()])
    assert "ENGINE" in frame and "virtual-cluster/16" in frame
    assert "COMPILES" in frame and "DISP99" in frame
    # The host node renders in the node table, not the engine pane.
    assert frame.index("10.0.0.1:9001") < frame.index("ENGINE")


def test_clustertop_tolerates_pre_ledger_engine_snapshots():
    # Snapshots written by pre-ledger code: no "engine" key at all, or a
    # bare/partial section — dashes and omissions, never a crash.
    legacy = {
        "node": "virtual-cluster/64", "configuration_id": 1,
        "membership_size": 64, "health": "stable",
        "metrics": {}, "transport": {}, "recorder": None,
    }
    frame = clustertop.render_frame([legacy])
    assert "ENGINE" not in frame  # no engine data -> no pane
    partial = dict(legacy)
    partial["engine"] = {"compile": {}, "memory": None}
    frame = clustertop.render_frame([partial])
    assert "ENGINE" in frame
    row = _engine_pane_row(frame, "virtual-cluster/64")
    assert "-" in row


def _engine_pane_row(frame: str, node: str) -> str:
    """The node's row INSIDE the engine pane (the node table above also
    carries the node name)."""
    lines = frame.splitlines()
    start = next(i for i, line in enumerate(lines) if line.startswith("ENGINE"))
    return next(line for line in lines[start:] if line.startswith(node))


def test_engine_pane_cache_hit_rate_and_memory_formatting():
    snapshot = {
        "node": "virtual-cluster/1000", "configuration_id": 1,
        "membership_size": 1000, "health": "stable",
        "metrics": {
            "engine_dispatches": 12,
            "engine_h2d_bytes": 3 << 20,
            "engine_d2h_bytes": 2048,
            "engine_dispatch_ms": {
                "run_to_decision": _hist_summary(5.0, 7.0, 100.0),
            },
        },
        "engine": {
            "compile": {"compiles": 9, "persistent_cache_hits": 3,
                        "persistent_cache_misses": 1},
            "memory": {"live_buffer_bytes": 5 << 30,
                       "device_bytes_in_use": 1 << 30},
        },
        "transport": {}, "recorder": None,
    }
    frame = clustertop.render_frame([snapshot])
    row = _engine_pane_row(frame, "virtual-cluster/1000")
    assert "75%" in row  # 3 hits / 4 lookups
    assert "3.0M" in row and "2.0K" in row
    assert "5.00G" in row and "1.00G" in row
    merged = LogHistogram()
    for v in (5.0, 7.0, 100.0):
        merged.observe(v)
    assert f"{merged.quantile(0.99):.1f}" in row


def _hist_summary(*values_ms):
    hist = LogHistogram()
    for value in values_ms:
        hist.observe(value)
    return hist.summary()


# ---------------------------------------------------------------------------
# Tenant-fleet tier (rapid_tpu/tenancy): per-tenant dispatch accounting
# ---------------------------------------------------------------------------

#: The fleet scrape's complete metric-name vocabulary — the single-cluster
#: golden list plus the tenancy tier (tenant counters zero-filled, tenant
#: count + per-dispatch throughput gauges) minus the per-cluster
#: configuration-id gauge (a fleet has B configuration chains, observed via
#: TenantFleet.config_ids()). Same API rule: renaming one breaks scrape
#: configs.
GOLDEN_FLEET_METRIC_NAMES = sorted(
    set(GOLDEN_ENGINE_METRIC_NAMES)
    - {"rapid_configuration_id"}
    | {
        "rapid_engine_tenant_cuts_total",
        "rapid_engine_tenant_rounds_total",
        "rapid_engine_tenant_rounds_per_dispatch",
        "rapid_engine_tenants",
        # Quarantine census (ISSUE 15): the zero-filled cumulative counter
        # and the current-census gauge are part of every fleet scrape from
        # the first snapshot — a quarantine must never mint a new series.
        "rapid_engine_tenant_quarantines_total",
        "rapid_engine_tenants_quarantined",
    }
)


def _fleet(b=4):
    from rapid_tpu.tenancy import TenantFleet

    fleet = TenantFleet.create(
        b, 12, n_slots=16, k=3, cohorts=2, knobs=[(3, 1, 2)] * b
    )
    fleet.faults = fleet.faults._replace(
        crashed=fleet.faults.crashed.at[:, 3].set(True)
    )
    return fleet


def test_fleet_prometheus_names_are_golden():
    fleet = _fleet()
    fleet.step()
    fleet.run_to_decision(max_steps=32)
    names = exposition.metric_names(fleet.prometheus_text())
    assert names == GOLDEN_FLEET_METRIC_NAMES


def test_fleet_dispatch_histogram_carries_fleet_step_phase():
    # Satellite (ISSUE 10): engine_dispatch_ms gains the fleet phase labels
    # — per-tenant dispatch accounting rides the same bounded instrument,
    # keyed fleet_step / fleet_decision / fleet_wave.
    fleet = _fleet()
    for _ in range(5):
        fleet.step()
    fleet.run_to_decision(max_steps=8)
    fleet.run_until_membership(fleet.membership_sizes(), max_steps=8)
    family = fleet.metrics.phase_timings["engine_dispatch"]
    assert set(family) == {"fleet_step", "fleet_decision", "fleet_wave"}
    assert isinstance(family["fleet_step"], LogHistogram)
    assert family["fleet_step"].count == 5
    assert fleet.metrics.counters["engine_dispatches"] == 7


def test_fleet_snapshot_tenancy_section():
    fleet = _fleet()
    fleet.step()  # 4 tenants, 1 round each, one dispatch
    rounds, decided, _, _ = fleet.run_to_decision(max_steps=32)
    snap = fleet.telemetry_snapshot()
    tenancy = snap["engine"]["tenancy"]
    assert tenancy["tenants"] == 4
    assert tenancy["tenant_rounds_total"] == 4 + int(rounds.sum())
    assert tenancy["tenant_cuts_total"] == int(decided.sum()) == 4
    # Per-dispatch tenant throughput: tenant-rounds over dispatches.
    assert tenancy["tenant_rounds_per_dispatch"] == round(
        tenancy["tenant_rounds_total"] / 2, 3
    )
    json.dumps(snap)  # the --metrics-dump / clustertop artifact


def test_clustertop_engine_pane_shows_tenants():
    fleet = _fleet()
    fleet.step()
    vc = _cluster()
    vc.run_to_decision(max_steps=8)
    frame = clustertop.render_frame(
        [vc.telemetry_snapshot(), fleet.telemetry_snapshot()]
    )
    assert "TENANTS" in frame
    fleet_row = _engine_pane_row(frame, "tenant-fleet/4x16")
    assert fleet_row.split()[1] == "4"
    # A single-cluster snapshot dashes the column, never crashes.
    vc_row = _engine_pane_row(frame, "virtual-cluster/16")
    assert vc_row.split()[1] == "-"


# ---------------------------------------------------------------------------
# Streaming tier (rapid_tpu/serving): the stream section's golden names
# ---------------------------------------------------------------------------

#: The streaming scrape's complete metric-name vocabulary — the
#: single-cluster golden list plus the stream tier: the pipeline gauges
#: (rates NaN pre-drain so the series set is stable from the first scrape),
#: the zero-filled wave/cut counters, and the alert->commit latency
#: histogram. Same API rule: renaming one breaks scrape configs.
GOLDEN_STREAM_METRIC_NAMES = sorted(
    set(GOLDEN_ENGINE_METRIC_NAMES)
    | {
        "rapid_engine_stream_alert_to_commit_ms_bucket",
        "rapid_engine_stream_alert_to_commit_ms_count",
        "rapid_engine_stream_alert_to_commit_ms_sum",
        "rapid_engine_stream_cuts_total",
        "rapid_engine_stream_depth",
        "rapid_engine_stream_overlap_efficiency",
        "rapid_engine_stream_p99_alert_to_commit_ms",
        "rapid_engine_stream_rounds_per_wave",
        "rapid_engine_stream_view_changes_per_sec",
        "rapid_engine_stream_waves_completed",
        "rapid_engine_stream_waves_in_flight",
        "rapid_engine_stream_waves_submitted",
        "rapid_engine_stream_waves_total",
    }
)


def _streamed_cluster():
    from rapid_tpu.serving import PoissonChurn, StreamDriver

    vc = VirtualCluster.create(
        24, n_slots=32, k=3, h=3, l=1, cohorts=2, fd_threshold=2, seed=0
    )
    vc.assign_cohorts_roundrobin()
    driver = StreamDriver(vc, rounds_per_wave=2, depth=2)
    for wave in PoissonChurn(24, 32, rate=1.0, seed=4).waves(3):
        driver.submit(wave)
    driver.drain()
    return vc


def test_stream_prometheus_names_are_golden():
    vc = _streamed_cluster()
    names = exposition.metric_names(vc.prometheus_text())
    assert names == GOLDEN_STREAM_METRIC_NAMES


def test_stream_section_only_grows_series_when_attached():
    # A batch-only driver keeps the batch vocabulary — attaching a
    # StreamDriver is what opts a scrape into the stream tier.
    vc = _cluster()
    vc.step()
    names = exposition.metric_names(vc.prometheus_text())
    assert not any("stream" in name for name in names)
    assert names == GOLDEN_ENGINE_METRIC_NAMES


def test_stream_vocabulary_complete_from_attach_not_first_completion():
    # The alert->commit timer is minted lazily on the first wave
    # COMPLETION; the scrape must still carry the full stream vocabulary —
    # histogram triplet included, zero-count — from the moment the driver
    # attaches, or dashboards keyed on the golden names see the series set
    # change mid-run (the stable-series rule the counters follow).
    from rapid_tpu.serving import StreamDriver

    vc = _cluster()
    StreamDriver(vc, rounds_per_wave=2, depth=2)  # attach, zero traffic
    names = exposition.metric_names(vc.prometheus_text())
    assert names == GOLDEN_STREAM_METRIC_NAMES


def test_dispatch_phase_vocabulary_enforced_at_write_time():
    # Satellite (ISSUE 11): the phase vocabulary is enforced where it is
    # WRITTEN — a typo'd phase raises instead of silently minting a new
    # histogram series that every dashboard keyed on the known names would
    # miss.
    from rapid_tpu.utils.dispatch import ENGINE_DISPATCH_PHASES

    assert {"stream_enqueue", "stream_fetch"} <= ENGINE_DISPATCH_PHASES
    vc = _cluster()
    with pytest.raises(ValueError, match="unregistered engine dispatch phase"):
        with vc._dispatch("stream_enque"):  # the typo class under test
            pass
    # The registered pair lands in the shared family like every entrypoint.
    vc.stream_step()
    family = vc.metrics.phase_timings["engine_dispatch"]
    assert family["stream_enqueue"].count == 1


def test_clustertop_renders_stream_pane():
    vc = _streamed_cluster()
    frame = clustertop.render_frame([vc.telemetry_snapshot()])
    assert "STREAM" in frame and "OVERLAP" in frame and "INFLIGHT" in frame
    lines = frame.splitlines()
    start = next(i for i, l in enumerate(lines) if l.startswith("STREAM"))
    row = next(l for l in lines[start:] if l.startswith("virtual-cluster/"))
    cells = row.split()
    assert cells[1] == "0"  # nothing in flight after drain
    assert cells[2] == "3" and cells[3] == "3"  # submitted == completed


def test_clustertop_stream_pane_tolerates_pre_stream_snapshots():
    # Batch-only snapshots (no stream section) render no stream pane; a
    # pre-drain stream section (None rates) renders dashes, never a crash.
    vc = _cluster()
    frame = clustertop.render_frame([vc.telemetry_snapshot()])
    assert "INFLIGHT" not in frame
    pre_drain = {
        "node": "virtual-cluster/64", "metrics": {}, "transport": {},
        "recorder": None,
        "engine": {"stream": {
            "waves_submitted": 2, "waves_completed": 0, "waves_in_flight": 2,
            "view_changes_per_sec": None, "overlap_efficiency": None,
            "p99_alert_to_commit_ms": None,
        }},
    }
    frame = clustertop.render_frame([pre_drain])
    assert "INFLIGHT" in frame
    lines = frame.splitlines()
    start = next(i for i, l in enumerate(lines) if l.startswith("STREAM"))
    row = next(l for l in lines[start:] if l.startswith("virtual-cluster/64"))
    assert "-" in row  # the undrained rates dash


def test_engine_counters_zero_filled_only_for_engine_snapshots():
    # A host snapshot must NOT grow engine series; an engine snapshot
    # exposes them even before the first dispatch.
    host = {"node": "h", "metrics": {}, "transport": {}, "recorder": None}
    host_names = exposition.metric_names(exposition.prometheus_text(host))
    assert not any("engine" in name for name in host_names)
    vc = _cluster()  # no dispatch at all yet
    names = exposition.metric_names(vc.prometheus_text())
    assert "rapid_engine_dispatches_total" in names
    assert "rapid_engine_steps_total" in names


# ---------------------------------------------------------------------------
# Device telemetry plane (rapid_tpu/models/state.TelemetryLanes): the
# activity section's golden names
# ---------------------------------------------------------------------------

#: The device-telemetry-plane vocabulary a ``telemetry=1`` scrape adds: the
#: per-round activity counters, the derived rate/peak gauges, the
#: fast/classic decision-path split, and the rounds-undecided log2
#: histogram. Present exactly when the driver carries the lanes; a
#: telemetry=0 scrape's name set is unchanged (the stable-series rule).
#: Same API rule as every golden list here: renaming one breaks scrape
#: configs.
GOLDEN_ACTIVITY_METRIC_NAMES = [
    "rapid_engine_activity_active_fraction",
    "rapid_engine_activity_active_peak",
    "rapid_engine_activity_active_sum_total",
    "rapid_engine_activity_alerts_total",
    "rapid_engine_activity_conflict_rate",
    "rapid_engine_activity_conflict_rounds_total",
    "rapid_engine_activity_fast_path_share",
    "rapid_engine_activity_invalidations_total",
    "rapid_engine_activity_peak_active_fraction",
    "rapid_engine_activity_proposals_total",
    "rapid_engine_activity_rounds_total",
    "rapid_engine_activity_rounds_undecided_total",
    "rapid_engine_activity_tally_sum_total",
    "rapid_engine_activity_winning_tally_mean",
    "rapid_engine_decision_path_total",
]


def _telemetry_cluster():
    vc = VirtualCluster.create(
        16, k=3, h=3, l=1, cohorts=2, fd_threshold=2, seed=0, telemetry=True
    )
    vc.assign_cohorts_roundrobin()
    return vc


def test_activity_names_golden_and_zero_filled_from_attach():
    # The full activity vocabulary exists before any sync boundary (the
    # host-side cache is zero-minted at attach), every sample at 0 — one
    # step only mints the shared dispatch histogram, never an activity
    # value: the scrape reads the cache, not the device lanes.
    vc = _telemetry_cluster()
    vc.step()
    text = vc.prometheus_text()
    names = exposition.metric_names(text)
    assert names == sorted(
        set(GOLDEN_ENGINE_METRIC_NAMES) | set(GOLDEN_ACTIVITY_METRIC_NAMES)
    )
    activity_samples = [
        line for line in text.splitlines()
        if line.startswith(("rapid_engine_activity", "rapid_engine_decision"))
    ]
    assert activity_samples
    assert all(line.split()[-1] in ("0", "0.0") for line in activity_samples)
    # And a telemetry=0 scrape is untouched — no activity names, ever
    # (pinned against the same golden list the pre-telemetry engine used).
    plain = _cluster()
    plain.step()
    assert exposition.metric_names(
        plain.prometheus_text()
    ) == GOLDEN_ENGINE_METRIC_NAMES


def test_activity_series_measure_after_the_sync_boundary():
    vc = _telemetry_cluster()
    vc.crash([3])
    vc.run_to_decision(max_steps=32)
    # The scrape reads the HOST cache: still zero until a sync boundary.
    before = vc.prometheus_text()
    assert 'rapid_engine_decision_path_total{node="virtual-cluster/16",' \
        'path="fast"} 0' in before
    vc.sync()
    text = vc.prometheus_text()
    assert 'path="fast"} 1' in text
    assert 'path="classic"} 0' in text
    rounds_line = next(
        line for line in text.splitlines()
        if line.startswith("rapid_engine_activity_rounds_total")
    )
    assert int(rounds_line.split()[-1]) > 0


def test_fleet_activity_carries_per_tenant_labels():
    from rapid_tpu.tenancy import TenantFleet

    fleet = TenantFleet.create(
        4, 12, n_slots=16, k=3, cohorts=2, knobs=[(3, 1, 2)] * 4,
        telemetry=True,
    )
    fleet.faults = fleet.faults._replace(
        crashed=fleet.faults.crashed.at[:, 3].set(True)
    )
    fleet.run_to_decision(max_steps=32)
    fleet.sync()
    text = fleet.prometheus_text()
    names = exposition.metric_names(text)
    assert names == sorted(
        set(GOLDEN_FLEET_METRIC_NAMES) | set(GOLDEN_ACTIVITY_METRIC_NAMES)
    )
    # The aggregate renders unlabelled; every tenant gets its own variant.
    for t in range(4):
        assert f'tenant="{t}"' in text
    tenant_fast = [
        line for line in text.splitlines()
        if line.startswith("rapid_engine_decision_path_total")
        and 'path="fast"' in line and "tenant=" in line
    ]
    assert len(tenant_fast) == 4
    assert all(line.split()[-1] == "1" for line in tenant_fast)
