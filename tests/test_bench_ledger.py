"""bench.py end-to-end through the run ledger: a CPU run emits a COMPLETE
JSONL ledger (every stage bracketed, provenance stamped, metric + run_end
recorded), and a wedged accelerator fails LOUDLY — nonzero exit with the
ledger pointing at the last completed stage — unless snapshot replay or CPU
fallback is explicitly authorized (the acceptance surface of ROADMAP open
item 2's "fail loudly rather than silently replaying snapshots").
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

import bench
from rapid_tpu.utils.ledger import (
    LedgerEvent,
    RunLedger,
    last_completed_stage,
    open_stage,
    read_ledger,
)

REPO = Path(__file__).resolve().parent.parent
BENCH = str(REPO / "bench.py")


def _run_bench(tmp_path, *args, env_overrides=None, drop=(), timeout=240):
    env = dict(os.environ)
    for name in list(env):
        if name.startswith("RAPID_TPU_BENCH"):
            del env[name]
    for name in drop:
        env.pop(name, None)
    env["RAPID_TPU_BENCH_LEDGER"] = str(tmp_path / "ledger.jsonl")
    env.update(env_overrides or {})
    proc = subprocess.run(
        [sys.executable, BENCH, *args],
        capture_output=True, text=True, env=env, timeout=timeout,
        cwd=str(tmp_path),
    )
    events, skipped = read_ledger(str(tmp_path / "ledger.jsonl"))
    assert skipped == 0, f"unparseable ledger lines: {skipped}"
    return proc, events


def _stage_pairs(events):
    """{stage: [(begin, close)]} where close is the matching end/fail."""
    pairs = {}
    for record in events:
        kind = record.get("event")
        if kind == "stage_begin":
            pairs.setdefault(record["stage"], []).append([record, None])
        elif kind in ("stage_end", "stage_fail"):
            spans = pairs.get(record["stage"], [])
            open_spans = [s for s in spans if s[1] is None]
            assert open_spans, f"{kind} without begin: {record}"
            open_spans[-1][1] = record
    return pairs


def test_cpu_run_emits_complete_ledger(tmp_path):
    """The acceptance criterion: a CPU-fallback bench run leaves a complete
    ledger — every stage begin+end, provenance stamped, derived metrics
    plausible — and its JSON line agrees with the ledger's metric event.
    One subprocess run also pins the ISSUE-9 headline path: the xl_point
    stage runs ramped-down on CPU (explicit marker, device-memory event
    alongside) and the opt-in stretch point runs in its own registered
    stage."""
    proc, events = _run_bench(
        tmp_path,
        env_overrides={
            "JAX_PLATFORMS": "cpu",
            "RAPID_TPU_BENCH_N": "256",
            # Tiny headline + stretch points: the FULL stage path runs
            # (ramped) without hardware-scale minutes. The stretch N equals
            # the headline N so the stretch stage reuses the compiled
            # executable (the stage path is what's under test, not a second
            # compile); the loss variant is dropped to keep this e2e's wall
            # clock near the pre-headline budget.
            "RAPID_TPU_BENCH_XL_N": "256",
            "RAPID_TPU_BENCH_STRETCH": "256",
            "RAPID_TPU_BENCH_XL_BUDGET_S": "100000",
            "RAPID_TPU_BENCH_NO_LOSS": "1",
            # Tiny tenant fleet: the FULL stage path runs (ramped) — one
            # warm-up + one timed lockstep wave over 4 mixed-scenario
            # tenants.
            "RAPID_TPU_BENCH_FLEET_B": "4",
            "RAPID_TPU_BENCH_FLEET_N": "48",
            # Tiny stream: the FULL pipelined path runs (ramped) — Poisson
            # churn double-buffered through both the single-cluster and
            # fleet stream drivers.
            "RAPID_TPU_BENCH_STREAM_WAVES": "6",
            "RAPID_TPU_BENCH_STREAM_N": "48",
            # Tiny adversarial-chaos fleet: the FULL stage path runs
            # (ramped) — warm-up + timed fuzz round over 4 mixed hostile
            # scenarios, oracle-checked clean.
            "RAPID_TPU_BENCH_CHAOS_B": "4",
            # Tiny self-healing drill: the FULL recovery path runs
            # (ramped) — injected transient failure, simulated kill,
            # checkpoint resume, bit-identity check.
            "RAPID_TPU_BENCH_RECOVERY_N": "48",
            "RAPID_TPU_BENCH_RECOVERY_WAVES": "4",
            # Suppress the cost-model geometry ladder (ISSUE 18): the
            # fitted classes are gate territory (test_cost_model /
            # test_lint); here only the never-silently-absent contract is
            # under test, and the ladder would cost ~40 s of compiles.
            "RAPID_TPU_BENCH_COST_LADDER": "0",
        },
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    [metric_line] = [l for l in proc.stdout.splitlines()
                     if l.startswith("{") and '"metric"' in l]
    result = json.loads(metric_line)
    assert result["platform"] == "cpu" and result["n_members"] == 256

    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_begin" and kinds[-1] == "run_end"
    begin = events[0]
    # Provenance: attributable to the exact source that produced it.
    assert begin["git_rev"] and begin["code_hash"]
    assert begin["hash_roots"] == ["bench.py", "rapid_tpu", "native"]
    # Every stage is bracketed: begin + end (or an explicit failure).
    pairs = _stage_pairs(events)
    for stage, spans in pairs.items():
        for span_begin, close in spans:
            assert close is not None, f"stage {stage} never closed"
            assert close["event"] == "stage_end"
            assert close["duration_ms"] >= 0
            assert span_begin.get("timeout_s", 0) > 0
    assert {"devices_init", "native_build", "state_build", "warmup_compile",
            "timed_samples", "rtt_probe"} <= set(pairs)
    assert open_stage(events) is None
    # Engine-tier events made it into the ledger.
    assert "compile_stats" in kinds and "device_memory" in kinds
    # The emitted JSON is also a ledger event (the trajectory's source of
    # truth survives even if stdout is lost).
    [metric_event] = [e for e in events if e["event"] == "metric"]
    assert metric_event["value"] == result["value"]
    # Derived metrics at the engine's cohort grain (the 4.96e10 bug class).
    assert abs(
        result["alert_deliveries_per_sec"]
        - result["alerts_per_sec"] * result["cohorts"]
    ) <= result["cohorts"]
    assert result["alert_deliveries_per_sec"] < 1e9
    assert result["compiles"] >= 1
    # ISSUE 9 headline path, same run: a ramped marker — never a fake 1M
    # number — with the measurement on the clearly-labeled xl_point_ms/xl_n
    # pair and device memory beside it; the stretch point is generic below
    # the named 10M goal.
    assert result["n1M_status"] == "ramped:256"
    assert "n1M_crash1pct_ms" not in result
    assert result["xl_n"] == 256 and result["xl_point_ms"] > 0
    assert "live_buffers" in result["xl_device_memory"]
    assert result["stretch_n"] == 256 and result["stretch_ms"] > 0
    assert "n10M_crash1pct_ms" not in result
    for stage in ("xl_point", "stretch_point"):
        [(span_begin, close)] = pairs[stage]
        assert close["event"] == "stage_end"
        assert span_begin["timeout_s"] > 0  # watchdog-enforced budget
        assert span_begin["n"] == 256  # each point stage records its own N
    assert any(
        e["event"] == "device_memory" and e.get("stage") == "xl_point"
        for e in events
    )
    # ISSUE 10 fleet path, same run: the tenant_fleet stage ran ramped-down
    # in its own bracketed, budgeted stage with an explicit status marker —
    # the fleet metric is never silently absent.
    assert result["tenant_fleet_status"] == "ramped:4x48"
    assert result["fleet_tenants"] == 4
    assert result["fleet_view_changes"] >= 4  # every tenant cut at least once
    assert result["tenant_view_changes_per_sec"] > 0
    assert "live_buffers" in result["fleet_device_memory"]
    [(fleet_begin, fleet_close)] = pairs["tenant_fleet"]
    assert fleet_close["event"] == "stage_end"
    assert fleet_begin["timeout_s"] > 0
    assert fleet_begin["n"] == 4 * 48  # total fleet slots under test
    assert any(
        e["event"] == "device_memory" and e.get("stage") == "tenant_fleet"
        for e in events
    )
    # ISSUE 11 streaming path, same run: the stream stage drove Poisson
    # churn through the pipelined dispatch path (both serving shapes) in
    # its own bracketed, budgeted stage — sustained view-changes/sec, p99
    # alert->commit, and the overlap-efficiency ratio all land in the
    # emitted JSON with an explicit status marker (never silently absent).
    assert result["stream_status"] == "ramped:6x48"
    assert result["stream_waves"] == 6 and result["stream_n"] == 48
    assert result["stream_view_changes_per_sec"] >= 0
    assert result["stream_p99_alert_to_commit_ms"] > 0
    assert 0.0 <= result["stream_overlap_efficiency"] <= 1.0
    assert result["stream_h2d_bytes"] > 0  # churn deltas crossed the seam
    [(stream_begin, stream_close)] = pairs["stream"]
    assert stream_close["event"] == "stage_end"
    assert stream_begin["timeout_s"] > 0
    assert stream_begin["n"] == 6 * 8  # engine rounds enqueued per path
    assert any(
        e["event"] == "device_memory" and e.get("stage") == "stream"
        for e in events
    )
    assert any(
        e["event"] == "compile_stats" and e.get("stage") == "stream"
        for e in events
    )
    # ISSUE 16 device-telemetry path, same run: the serving lanes measured
    # real activity — fractions in (0, 1] with an explicit "measured"
    # status, the zero-churn soak published as an explicit 0.0 (a
    # measurement, not an absence — perfview's activity-missing flag
    # polices exactly this), and the fleet half's pooled + per-tenant
    # conflict rates from the lanes the lockstep wave carried.
    assert result["activity_status"] == "measured"
    assert 0.0 < result["stream_active_fraction"] <= 1.0
    assert (
        result["stream_active_fraction"]
        <= result["stream_peak_active_fraction"]
        <= 1.0
    )
    assert 0.0 <= result["stream_fast_path_share"] <= 1.0
    assert result["quiescent_active_fraction"] == 0.0
    assert 0.0 <= result["tenant_conflict_rate"] <= 1.0
    assert len(result["tenant_conflict_rates"]) == result["fleet_tenants"]
    assert all(0.0 <= r <= 1.0 for r in result["tenant_conflict_rates"])
    assert 0.0 <= result["fleet_fast_path_share"] <= 1.0
    # ISSUE 12 adversarial-chaos path, same run: the chaos stage resolved
    # B mixed hostile scenarios (Byzantine false alerts, committee crashes,
    # honest churn) through batched fleet dispatches in its own bracketed,
    # budgeted stage — scenarios/sec lands in the emitted JSON with an
    # explicit status marker (never silently absent), zero violations.
    assert result["chaos_status"] == "ramped:4x12"
    assert result["chaos_tenants"] == 4
    assert result["chaos_scenarios_per_sec"] > 0
    assert result["chaos_wall_ms"] > 0
    assert result["chaos_dispatches"] >= 1
    assert result["chaos_families"] >= 1
    [(chaos_begin, chaos_close)] = pairs["chaos"]
    assert chaos_close["event"] == "stage_end"
    assert chaos_begin["timeout_s"] > 0
    assert chaos_begin["n"] == 4  # tenants (hostile scenarios) under test
    assert any(
        e["event"] == "compile_stats" and e.get("stage") == "chaos"
        for e in events
    )
    # ISSUE 15 self-healing path, same run: the recovery stage ran the
    # whole drill — transient failure retried on seeded backoff, simulated
    # kill between waves, checkpoint-cadence writes, deterministic resume
    # — in its own bracketed, budgeted stage with the MTTR and the
    # bit-identity verdict in the emitted JSON, never silently absent.
    assert result["recovery_status"] == "ramped:4x48"
    assert result["recovery_mttr_ms"] > 0
    assert result["recovery_bit_identical"] is True
    assert result["recovery_checkpoints"] >= 1
    assert result["recovery_retries"] >= 1
    assert result["recovery_killed_after_wave"] == 2  # waves//2
    assert result["recovery_resumed_wave"] >= 1
    [(recovery_begin, recovery_close)] = pairs["recovery"]
    assert recovery_close["event"] == "stage_end"
    assert recovery_begin["timeout_s"] > 0
    assert recovery_begin["n"] == 48
    # The supervisor's recovery timeline landed in the SAME ledger.
    recovery_kinds = [
        e["event"] for e in events if e.get("stage") == "recovery"
    ]
    assert "recovery_retry" in recovery_kinds
    assert "recovery_checkpoint" in recovery_kinds
    assert "recovery_resume" in recovery_kinds
    # ISSUE 13 memory path, same run: the hlo_audit stage (begin/end
    # bracketed above with every other stage) emits the state-compaction
    # memory axis end-to-end on CPU — bytes/member under all three
    # layouts, the run's total, the 100k->100M sizing table, and the
    # never-silently-absent mem_status.
    [(mem_begin, mem_close)] = pairs["hlo_audit"]
    assert mem_close["event"] == "stage_end"
    assert mem_begin["timeout_s"] > 0
    assert result["mem_status"]  # never silently absent
    assert 0 < result["bytes_per_member"] < result["bytes_per_member_wide"]
    assert result["bytes_per_member_packed"] < result["bytes_per_member"]
    # bytes_per_member is rounded in the JSON; the total is exact.
    assert abs(
        result["state_bytes_total"] - result["bytes_per_member"] * result["n_members"]
    ) <= result["n_members"]
    sizing = result["mem_sizing"]
    assert set(sizing) == {"100k", "1M", "10M", "100M"}
    for row in sizing.values():
        assert row["compact_gb"] < row["wide_gb"]
    # The 100M sizing is the ROADMAP deliverable: a concrete GB figure.
    assert sizing["100M"]["n"] == 100_000_000
    assert sizing["100M"]["compact_gb"] > 0
    # The audit compiled the compact entrypoints, so the status is the
    # measured one (memory_analysis argument bytes present for the pair).
    assert result["mem_status"] == "live:hlo-audit"
    assert result["hlo_audit"]["step_compact"]["argument_bytes"] < (
        result["hlo_audit"]["step"]["argument_bytes"]
    )
    # ISSUE 18 cost axis, same run and stage: quiescent_round_cost and
    # cost_fit are NEVER silently absent. The quiescent block is either
    # the measured sharded-step cost (when this run got the 8-device
    # mesh) or a named unavailability; the suppressed ladder names its
    # knob rather than vanishing.
    quiescent = result["quiescent_round_cost"]
    assert ("collective_payload_bytes" in quiescent) or (
        quiescent["status"].startswith("unavailable")
    ), quiescent
    assert result["cost_fit"] == {
        "status": "suppressed:RAPID_TPU_BENCH_COST_LADDER=0"
    }


def test_headline_plan_is_never_silently_absent(monkeypatch):
    """ISSUE 9: every branch of the headline policy yields an explicit
    status — unit-pinned so the skipped/suppressed paths don't need their
    own full bench subprocess."""
    for name in ("RAPID_TPU_BENCH_NO_XL", "RAPID_TPU_BENCH_XL",
                 "RAPID_TPU_BENCH_XL_N", "RAPID_TPU_BENCH_XL_BUDGET_S"):
        monkeypatch.delenv(name, raising=False)
    assert bench.headline_plan("tpu", 0.0) == (1_000_000, "live")
    assert bench.headline_plan("cpu", 0.0) == (4096, "ramped:4096")
    monkeypatch.setenv("RAPID_TPU_BENCH_XL_N", "256")
    assert bench.headline_plan("cpu", 0.0) == (256, "ramped:256")
    # Past the XL budget the point is skipped — but NAMED.
    assert bench.headline_plan("tpu", 2000.0) == (0, "skipped-budget")
    # ...unless explicitly forced.
    monkeypatch.setenv("RAPID_TPU_BENCH_XL", "1")
    assert bench.headline_plan("cpu", 2000.0) == (1_000_000, "live")
    monkeypatch.setenv("RAPID_TPU_BENCH_NO_XL", "1")
    assert bench.headline_plan("tpu", 0.0) == (0, "suppressed")


def test_fleet_plan_is_never_silently_absent(monkeypatch):
    """ISSUE 10: every branch of the tenant-fleet policy yields an explicit
    status (the headline_plan discipline) — live at 256x1024 on the
    accelerator, ramped on CPU, skipped-budget past the (shared-default)
    budget, suppressed on request, forced when asked."""
    for name in ("RAPID_TPU_BENCH_NO_FLEET", "RAPID_TPU_BENCH_FLEET",
                 "RAPID_TPU_BENCH_FLEET_B", "RAPID_TPU_BENCH_FLEET_N",
                 "RAPID_TPU_BENCH_FLEET_BUDGET_S",
                 "RAPID_TPU_BENCH_XL_BUDGET_S"):
        monkeypatch.delenv(name, raising=False)
    assert bench.fleet_plan("tpu", 0.0) == (256, 1024, "live")
    assert bench.fleet_plan("cpu", 0.0) == (8, 64, "ramped:8x64")
    monkeypatch.setenv("RAPID_TPU_BENCH_FLEET_B", "4")
    monkeypatch.setenv("RAPID_TPU_BENCH_FLEET_N", "48")
    assert bench.fleet_plan("cpu", 0.0) == (4, 48, "ramped:4x48")
    # Past the budget the point is skipped — but NAMED; the fleet budget
    # defaults to the XL budget so one env override governs both tails.
    assert bench.fleet_plan("tpu", 2000.0) == (0, 0, "skipped-budget")
    monkeypatch.setenv("RAPID_TPU_BENCH_FLEET_BUDGET_S", "3000")
    assert bench.fleet_plan("tpu", 2000.0)[2] == "live"
    # ...and forcing runs it anywhere, at the live scale.
    monkeypatch.setenv("RAPID_TPU_BENCH_FLEET_BUDGET_S", "1")
    monkeypatch.setenv("RAPID_TPU_BENCH_FLEET", "1")
    assert bench.fleet_plan("cpu", 2000.0) == (4, 48, "live")
    monkeypatch.setenv("RAPID_TPU_BENCH_NO_FLEET", "1")
    assert bench.fleet_plan("tpu", 0.0) == (0, 0, "suppressed")


def test_stream_plan_is_never_silently_absent(monkeypatch):
    """ISSUE 11: every branch of the streaming-serving policy yields an
    explicit status (the headline_plan discipline) — 64 waves at N=4096 on
    the accelerator, ramped on CPU, skipped-budget past the (shared-default)
    budget, suppressed on request, forced when asked."""
    for name in ("RAPID_TPU_BENCH_NO_STREAM", "RAPID_TPU_BENCH_STREAM",
                 "RAPID_TPU_BENCH_STREAM_WAVES", "RAPID_TPU_BENCH_STREAM_N",
                 "RAPID_TPU_BENCH_STREAM_BUDGET_S",
                 "RAPID_TPU_BENCH_XL_BUDGET_S"):
        monkeypatch.delenv(name, raising=False)
    assert bench.stream_plan("tpu", 0.0) == (64, 4096, "live")
    assert bench.stream_plan("cpu", 0.0) == (12, 96, "ramped:12x96")
    monkeypatch.setenv("RAPID_TPU_BENCH_STREAM_WAVES", "6")
    monkeypatch.setenv("RAPID_TPU_BENCH_STREAM_N", "48")
    assert bench.stream_plan("cpu", 0.0) == (6, 48, "ramped:6x48")
    # Past the budget the point is skipped — but NAMED; the stream budget
    # defaults to the XL budget so one env override governs all three tails.
    assert bench.stream_plan("tpu", 2000.0) == (0, 0, "skipped-budget")
    monkeypatch.setenv("RAPID_TPU_BENCH_STREAM_BUDGET_S", "3000")
    assert bench.stream_plan("tpu", 2000.0)[2] == "live"
    # ...and forcing runs it anywhere, at the env-resolved scale.
    monkeypatch.setenv("RAPID_TPU_BENCH_STREAM_BUDGET_S", "1")
    monkeypatch.setenv("RAPID_TPU_BENCH_STREAM", "1")
    assert bench.stream_plan("cpu", 2000.0) == (6, 48, "live")
    monkeypatch.setenv("RAPID_TPU_BENCH_NO_STREAM", "1")
    assert bench.stream_plan("tpu", 0.0) == (0, 0, "suppressed")


def test_chaos_plan_is_never_silently_absent(monkeypatch):
    """ISSUE 12: every branch of the adversarial-chaos policy yields an
    explicit status (the headline_plan discipline) — 256 mixed hostile
    scenarios per fleet on the accelerator, ramped on CPU, skipped-budget
    past the (shared-default) budget, suppressed on request, forced when
    asked."""
    for name in ("RAPID_TPU_BENCH_NO_CHAOS", "RAPID_TPU_BENCH_CHAOS",
                 "RAPID_TPU_BENCH_CHAOS_B", "RAPID_TPU_BENCH_CHAOS_BUDGET_S",
                 "RAPID_TPU_BENCH_XL_BUDGET_S"):
        monkeypatch.delenv(name, raising=False)
    assert bench.chaos_plan("tpu", 0.0) == (256, "live")
    assert bench.chaos_plan("cpu", 0.0) == (12, "ramped:12x12")
    monkeypatch.setenv("RAPID_TPU_BENCH_CHAOS_B", "4")
    assert bench.chaos_plan("cpu", 0.0) == (4, "ramped:4x12")
    # Past the budget the stage is skipped — but NAMED; the chaos budget
    # defaults to the XL budget so one env override governs every tail.
    assert bench.chaos_plan("tpu", 2000.0) == (0, "skipped-budget")
    monkeypatch.setenv("RAPID_TPU_BENCH_CHAOS_BUDGET_S", "3000")
    assert bench.chaos_plan("tpu", 2000.0)[1] == "live"
    # ...and forcing runs it anywhere, at the env-resolved scale.
    monkeypatch.setenv("RAPID_TPU_BENCH_CHAOS_BUDGET_S", "1")
    monkeypatch.setenv("RAPID_TPU_BENCH_CHAOS", "1")
    assert bench.chaos_plan("cpu", 2000.0) == (4, "live")
    monkeypatch.setenv("RAPID_TPU_BENCH_NO_CHAOS", "1")
    assert bench.chaos_plan("tpu", 0.0) == (0, "suppressed")


def test_recovery_plan_is_never_silently_absent(monkeypatch):
    """ISSUE 15: every branch of the self-healing drill policy yields an
    explicit status (the headline_plan discipline) — N=4096 x 16 waves on
    the accelerator, ramped on CPU, skipped-budget past the
    (shared-default) budget, suppressed on request, forced when asked."""
    for name in ("RAPID_TPU_BENCH_NO_RECOVERY", "RAPID_TPU_BENCH_RECOVERY",
                 "RAPID_TPU_BENCH_RECOVERY_N",
                 "RAPID_TPU_BENCH_RECOVERY_WAVES",
                 "RAPID_TPU_BENCH_RECOVERY_BUDGET_S",
                 "RAPID_TPU_BENCH_XL_BUDGET_S"):
        monkeypatch.delenv(name, raising=False)
    assert bench.recovery_plan("tpu", 0.0) == (4096, 16, "live")
    assert bench.recovery_plan("cpu", 0.0) == (64, 6, "ramped:6x64")
    monkeypatch.setenv("RAPID_TPU_BENCH_RECOVERY_N", "32")
    monkeypatch.setenv("RAPID_TPU_BENCH_RECOVERY_WAVES", "4")
    assert bench.recovery_plan("cpu", 0.0) == (32, 4, "ramped:4x32")
    # Past the budget the stage is skipped — but NAMED; the recovery
    # budget defaults to the XL budget so one override governs every tail.
    assert bench.recovery_plan("tpu", 2000.0) == (0, 0, "skipped-budget")
    monkeypatch.setenv("RAPID_TPU_BENCH_RECOVERY_BUDGET_S", "3000")
    assert bench.recovery_plan("tpu", 2000.0)[2] == "live"
    # ...and forcing runs it anywhere, at the env-resolved scale.
    monkeypatch.setenv("RAPID_TPU_BENCH_RECOVERY_BUDGET_S", "1")
    monkeypatch.setenv("RAPID_TPU_BENCH_RECOVERY", "1")
    assert bench.recovery_plan("cpu", 2000.0) == (32, 4, "live")
    monkeypatch.setenv("RAPID_TPU_BENCH_NO_RECOVERY", "1")
    assert bench.recovery_plan("tpu", 0.0) == (0, 0, "suppressed")


def test_activity_status_is_never_silently_absent():
    """ISSUE 16: every branch of the device-telemetry status policy yields
    an explicit marker — "measured" iff the stream stage fetched a numeric
    active fraction, the stage's own skip reason otherwise — unit-pinned so
    the skipped/suppressed paths don't need their own bench subprocess."""
    assert bench.activity_status(
        {"stream_active_fraction": 0.0417}, "ramped:6x48"
    ) == "measured"
    # 0.0 is a measurement (the quiescent soak), never an absence.
    assert bench.activity_status(
        {"stream_active_fraction": 0.0}, "ramped:6x48"
    ) == "measured"
    assert bench.activity_status({}, "ramped:12x96") == "ramped:12x96"
    assert bench.activity_status({}, "skipped-budget") == "skipped-budget"
    assert bench.activity_status({}, "suppressed") == "suppressed"
    assert bench.activity_status(
        {"stream_active_fraction": None}, "suppressed"
    ) == "suppressed"


def test_memory_report_status_is_never_silently_absent():
    """ISSUE 13: memory_report is pure over (audit table, geometry) and
    always yields a mem_status — measured when the audit carries argument
    bytes for the wide+compact step pair, an explicit computed:<why>
    marker otherwise (audit errored, absent, or lacking memory analysis)."""
    geometry = dict(n=1024, k_rings=10, cohorts=8)
    live = bench.memory_report(
        {"step": {"argument_bytes": 1000}, "step_compact": {"argument_bytes": 600}},
        **geometry,
    )
    assert live["mem_status"] == "live:hlo-audit"
    assert 0 < live["bytes_per_member"] < live["bytes_per_member_wide"]
    assert set(live["mem_sizing"]) == {"100k", "1M", "10M", "100M"}

    errored = bench.memory_report({"error": "needs 8 devices"}, **geometry)
    assert errored["mem_status"].startswith("computed:")
    assert errored["bytes_per_member"] == live["bytes_per_member"]

    partial = bench.memory_report(
        {"step": {"argument_bytes": None}, "step_compact": {}}, **geometry
    )
    assert partial["mem_status"] == "computed:audit-lacks-step-memory"

    # The sizing ladder re-derives the policy per N: the 100M row's
    # bytes/member EXCEEDS the small-N row's (index lanes re-widen to
    # int32 past 32k slots) — the table is honest, not an extrapolation.
    assert (
        live["mem_sizing"]["100M"]["bytes_per_member"]
        > live["bytes_per_member"]
    )


def test_parse_scale_spellings():
    assert bench._parse_scale("10M") == 10_000_000
    assert bench._parse_scale("10m") == 10_000_000
    assert bench._parse_scale("250k") == 250_000
    assert bench._parse_scale("4096") == 4096
    assert bench._parse_scale("gibberish") == 0


_WEDGE_ENV = {
    "RAPID_TPU_BENCH_SIMULATE_WEDGE": "1",
    "RAPID_TPU_BENCH_INIT_TIMEOUT_S": "2",
    "RAPID_TPU_BENCH_ATTEMPTS": "1",
}


def test_wedge_exits_nonzero_without_allow_snapshot(tmp_path):
    proc, events = _run_bench(
        tmp_path, env_overrides=_WEDGE_ENV, drop=("JAX_PLATFORMS",),
        timeout=120,
    )
    assert proc.returncode == 1
    assert "no fallback authorized" in proc.stderr
    # The one stdout JSON line is an explicit error, never a number.
    [line] = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    error = json.loads(line)
    assert error["error"] == "accelerator_wedged"
    assert "last_completed_stage" in error
    kinds = [e["event"] for e in events]
    assert "watchdog_kill" in kinds
    assert kinds[-1] == "run_fail"
    [fail] = [e for e in events if e["event"] == "run_fail"]
    assert fail["outcome"] == "wedged"
    assert fail["last_completed_stage"] == last_completed_stage(events)
    assert "snapshot_replay" not in kinds  # nothing replayed silently


def test_wedge_failure_is_scoped_to_this_run(tmp_path):
    # The default ledger path accumulates runs across invocations: a wedge
    # with ZERO completed stages must report none — never a PREVIOUS run's
    # last stage (and the watchdog must not inherit its open stages).
    ledger_path = tmp_path / "ledger.jsonl"
    old = RunLedger(str(ledger_path), run_id="previous-run")
    old.emit(LedgerEvent.RUN_BEGIN, mode="inline")
    with old.stage("state_build", timeout_s=900):
        pass
    old.emit(LedgerEvent.STAGE_BEGIN, stage="warmup_compile", timeout_s=900)
    old.close()  # a previous run that died mid-warmup
    proc, events = _run_bench(
        tmp_path, env_overrides=_WEDGE_ENV, drop=("JAX_PLATFORMS",),
        timeout=120,
    )
    assert proc.returncode == 1
    [line] = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert json.loads(line)["last_completed_stage"] is None
    [fail] = [e for e in events if e["event"] == "run_fail"
              and e["run_id"] != "previous-run"]
    assert fail["last_completed_stage"] is None


@pytest.mark.slow
def test_wedge_with_cpu_fallback_reruns_and_closes_the_run(tmp_path):
    # --cpu-fallback: the watchdog parent execve's into a CPU continuation
    # sharing the run id; the successful fallback must CLOSE the run
    # (run_end outcome=cpu_fallback) — without it the ledger ends at
    # run_fail and the run reads as failed despite a real measurement.
    # Rides the unfiltered check.sh pass (~20 s wall: a second full bench
    # subprocess); the wedge-exits-nonzero and snapshot-replay wedge tests
    # keep the LOUD-failure contract in tier-1.
    proc, events = _run_bench(
        tmp_path, "--cpu-fallback",
        env_overrides={
            **_WEDGE_ENV,
            "RAPID_TPU_BENCH_N": "256",
            "RAPID_TPU_BENCH_XL_BUDGET_S": "0",
        },
        drop=("JAX_PLATFORMS",), timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    [line] = [l for l in proc.stdout.splitlines()
              if l.startswith("{") and '"metric"' in l]
    assert json.loads(line)["platform"] == "cpu"
    kinds = [e["event"] for e in events]
    # The wedge is on record AND the run is closed by the fallback.
    assert "run_fail" in kinds
    assert kinds[-1] == "run_end"
    [end] = [e for e in events if e["event"] == "run_end"]
    assert end["outcome"] == "cpu_fallback"
    assert len({e["run_id"] for e in events}) == 1  # one run, one id


def test_wedge_with_allow_snapshot_replays_and_marks_ledger(tmp_path):
    capture = tmp_path / "capture.json"
    capture.write_text(json.dumps({
        "metric": "churn_resolution_ms_n100000_churn5pct", "value": 100.9,
        "unit": "ms", "platform": "tpu", "n_members": 100_000,
        "captured_at": "2026-07-29T14:06:21Z", "vs_baseline": 4.957,
    }))
    proc, events = _run_bench(
        tmp_path, "--allow-snapshot",
        env_overrides={**_WEDGE_ENV, "RAPID_TPU_BENCH_SNAPSHOT": str(capture)},
        drop=("JAX_PLATFORMS",), timeout=120,
    )
    assert proc.returncode == 0
    [line] = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    replayed = json.loads(line)
    # Unstamped capture: stale, renamed, demoted — and the ledger says so.
    assert replayed["stale_code"] is True
    assert replayed["metric"].endswith("_snapshot")
    [mark] = [e for e in events if e["event"] == "snapshot_replay"]
    assert mark["stale_code"] is True
    assert mark["snapshot_path"]
    # run_fail precedes the replay (the wedge stays on record), and the
    # successful replay CLOSES the run — perfview's outcome is the latest
    # terminal event, so an rc-0 replay must not read as FAILED.
    kinds = [e["event"] for e in events]
    assert kinds.index("run_fail") < kinds.index("snapshot_replay")
    assert kinds[-1] == "run_end"
    [end] = [e for e in events if e["event"] == "run_end"]
    assert end["outcome"] == "snapshot_replay"


def test_ledger_event_vocabulary_is_enforced_in_bench(tmp_path):
    # The runtime guard behind the lint rule: bench cannot invent events.
    from rapid_tpu.utils.ledger import RunLedger

    ledger = RunLedger(str(tmp_path / "l.jsonl"))
    with pytest.raises(TypeError):
        ledger.emit("made_up_event")
    ledger.close()


def test_stage_timeouts_table_covers_all_stages():
    from rapid_tpu.utils.ledger import STAGE_NAMES

    assert set(bench.STAGE_TIMEOUTS_S) == set(STAGE_NAMES)
    assert all(v > 0 for v in bench.STAGE_TIMEOUTS_S.values())


def test_parse_args_flags_and_env_aliases(monkeypatch):
    for name in ("RAPID_TPU_BENCH_ALLOW_SNAPSHOT", "RAPID_TPU_BENCH_CPU_FALLBACK",
                 "RAPID_TPU_BENCH_PROFILE"):
        monkeypatch.delenv(name, raising=False)
    args = bench._parse_args([])
    assert not args.allow_snapshot and not args.cpu_fallback
    assert args.profile is None
    args = bench._parse_args(["--allow-snapshot", "--cpu-fallback",
                              "--profile", "/tmp/prof", "--ledger", "x.jsonl"])
    assert args.allow_snapshot and args.cpu_fallback
    assert args.profile == "/tmp/prof" and args.ledger == "x.jsonl"
    monkeypatch.setenv("RAPID_TPU_BENCH_ALLOW_SNAPSHOT", "1")
    assert bench._parse_args([]).allow_snapshot
