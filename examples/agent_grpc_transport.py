"""Cluster agent over the interop gRPC transport — the transport-swap example.

The analog of the reference's second example agent
(examples/src/main/java/com/vrg/standalone/AgentWithNettyMessaging.java:57-66),
which constructs the alternate messaging client/server explicitly and hands
them to the cluster builder to prove the messaging SPI seam. Here the swapped
transport is ``rapid_tpu.interop.grpc_transport`` — real grpc.aio serving the
reference's exact RPC (``remoting.MembershipService/sendRequest``) — so the
same protocol stack runs under gRPC tooling (proxies, interceptors,
channelz) with zero protocol-layer changes.

Run a 3-node cluster on localhost:

    python examples/agent_grpc_transport.py --listen-address 127.0.0.1:9101 \
        --seed-address 127.0.0.1:9101 &
    python examples/agent_grpc_transport.py --listen-address 127.0.0.1:9102 \
        --seed-address 127.0.0.1:9101 &
    python examples/agent_grpc_transport.py --listen-address 127.0.0.1:9103 \
        --seed-address 127.0.0.1:9101 &
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from rapid_tpu.interop.grpc_transport import GrpcClient, GrpcServer
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.protocol.events import ClusterEvents
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint

LOG = logging.getLogger("agent_grpc")


async def run(args) -> None:
    listen = Endpoint.parse(args.listen_address)
    seed = Endpoint.parse(args.seed_address)
    settings = Settings()

    # The transport swap: build the alternate client/server explicitly and
    # hand them to the cluster builder (the messaging SPI seam —
    # AgentWithNettyMessaging.java:57-66 does exactly this with Netty).
    client = GrpcClient(listen, settings)
    server = GrpcServer(listen)

    if listen == seed:
        LOG.info("starting cluster as seed at %s (gRPC transport)", listen)
        cluster = await Cluster.start(
            listen, settings=settings, client=client, server=server
        )
    else:
        LOG.info("joining cluster at %s from %s (gRPC transport)", seed, listen)
        cluster = await Cluster.join(
            seed, listen, settings=settings, client=client, server=server
        )

    def log_event(event):
        def callback(change):
            LOG.info(
                "%s: config %d, %d members, delta: %s",
                event.name,
                change.configuration_id,
                len(change.membership),
                [(str(sc.endpoint), sc.status.name) for sc in change.status_changes],
            )

        return callback

    for event in (
        ClusterEvents.VIEW_CHANGE_PROPOSAL,
        ClusterEvents.VIEW_CHANGE,
        ClusterEvents.KICKED,
    ):
        cluster.register_subscription(event, log_event(event))

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)

    async def reporter():
        while not stop.is_set():
            LOG.info(
                "membership size: %d (config %d)",
                cluster.membership_size,
                cluster.service.view.configuration_id,
            )
            await asyncio.sleep(args.report_interval)

    reporter_task = asyncio.ensure_future(reporter())
    await stop.wait()
    reporter_task.cancel()
    LOG.info("leaving gracefully")
    await cluster.leave_gracefully()


def main() -> None:
    parser = argparse.ArgumentParser(description="rapid_tpu agent on the gRPC transport")
    parser.add_argument("--listen-address", required=True, help="host:port to listen on")
    parser.add_argument("--seed-address", required=True,
                        help="host:port of the seed (same as listen-address to bootstrap)")
    parser.add_argument("--report-interval", type=float, default=1.0)
    args = parser.parse_args()
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )
    asyncio.run(run(args))


if __name__ == "__main__":
    main()
