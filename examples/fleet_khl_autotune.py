"""Per-tenant K/H/L knob autotune over a tenant fleet — one sweep, one
dispatch per round.

``examples/khl_sensitivity.py`` sweeps (H, L) sequentially, one engine run
per cell; this example runs the whole candidate grid as ONE
:class:`~rapid_tpu.tenancy.TenantFleet` (one tenant per knob setting,
identical scenario) and picks the winner with the khl_sensitivity conflict
metric as the objective — the ``delivery_autotune.py`` winner-selection
shape (a per-candidate table + one ``best_knob`` field), batched.

Usage:

    python examples/fleet_khl_autotune.py [--n 1000] [--f 8] \
        [--knobs 9/4,8/3,7/2] [--spread 8] [--seed 0]

Prints one JSON line per seed (the ``rapid_tpu.tenancy.autotune.sweep_khl``
artifact).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=1000)
    parser.add_argument("--f", type=int, default=8,
                        help="simultaneous failures per scenario")
    parser.add_argument("--knobs", default="9/4,8/3,7/2,6/2,5/1",
                        help="comma-separated H/L candidates, one tenant each")
    parser.add_argument("--cohorts", type=int, default=16)
    parser.add_argument("--spread", type=int, default=8,
                        help="delivery-delay support (rounds) — the skew "
                        "that makes low H conflict-prone")
    parser.add_argument("--seeds", default="0",
                        help="comma-separated scenario seeds, one sweep each")
    parser.add_argument(
        "--platform", default="cpu",
        help="jax platform (default cpu: the sweep is small, and the forced "
        "override avoids wedging on a dead accelerator tunnel)",
    )
    args = parser.parse_args()

    from rapid_tpu.utils.platform import force_platform

    if not force_platform(args.platform):
        raise RuntimeError(
            f"could not force jax platform {args.platform!r} (a backend was "
            "already initialized); refusing to sweep on an unintended backend"
        )

    from rapid_tpu.tenancy.autotune import sweep_khl

    knob_grid = [
        tuple(int(part) for part in cell.split("/"))
        for cell in args.knobs.split(",")
    ]
    for seed in (int(s) for s in args.seeds.split(",")):
        result = sweep_khl(
            n=args.n, f=args.f, knob_grid=knob_grid, cohorts=args.cohorts,
            seed=seed, delivery_spread=args.spread,
        )
        print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
