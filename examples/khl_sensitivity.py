"""K/H/L sensitivity of almost-everywhere agreement (paper Fig. 11 analog).

The reference paper measures, by simulation at N=1000 over 20 repetitions per
combination, how often the multi-node cut detector yields *conflicting*
proposals (different nodes proposing different cuts) for K=10,
H in {6..9}, L in {1..4}, F concurrent failures in {2,4,8,16}: ~2% conflicts
at H-L=5 with F=2, improving ~4x per extra watermark gap.

This reproduces the experiment on the TPU engine: F crashed members,
per-edge detection jitter (staggered failure detectors), and 64 (default)
independently-diverging receiver cohorts — each with its own per-edge
delivery-delay draw (``delivery_spread``; optional one-way loss via
``loss``) — the sampled analog of the reference's N independent per-node
cut detectors (MultiNodeCutDetector.java:31-37). A run conflicts when more
than one distinct cut proposal was announced (the paper's metric) or no
decision landed within the round budget.

Usage: python examples/khl_sensitivity.py [--n 1000] [--reps 10] [--cohorts 64]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run_once(n, k, h, l, f, cohorts, seed, delivery_spread=1, stagger=1, loss=0.0,
             delay_permille=1000) -> tuple:
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    rng = np.random.default_rng(seed)
    vc = VirtualCluster.create(
        n, k=k, h=h, l=l, cohorts=cohorts, fd_threshold=2, seed=seed,
        delivery_spread=delivery_spread, delivery_prob_permille=delay_permille,
    )
    # Receivers split into cohorts; every cohort gets an independent
    # per-edge delivery-delay draw (delivery_spread). The paper's Fig. 11
    # simulation models pure timing divergence, so one-way loss defaults to
    # 0; pass loss > 0 to additionally blind each non-primary cohort to a
    # random fraction of sources.
    cohort_of = rng.integers(0, cohorts, size=n).astype(np.int32)
    vc.assign_cohorts(cohort_of)
    if loss > 0:
        rx_block = np.zeros((cohorts, vc.cfg.n), dtype=bool)
        for c in range(1, cohorts):
            rx_block[c] = rng.random(vc.cfg.n) < loss
        vc.set_rx_block(rx_block)

    victims = rng.choice(n, size=f, replace=False)
    vc.crash(victims)
    vc.stagger_fd_counts(rng, spread_rounds=stagger)

    proposals = set()
    for round_idx in range(64):
        events = vc.step()
        announced = np.asarray(events.proposals_announced)
        if announced.any():
            # Read the hashes from the EVENTS (pre-view-change capture): on a
            # deciding round, vc.state.prop_* is already reset to zeros.
            hi = np.asarray(events.prop_hi)
            lo = np.asarray(events.prop_lo)
            for ci in np.nonzero(announced)[0]:
                proposals.add((int(hi[ci]), int(lo[ci])))
        if bool(events.decided):
            # The paper's metric: did receivers PROPOSE different cuts?
            # (Fig. 11 counts conflicting proposals, not vote dissent.)
            return len(proposals) > 1, round_idx + 1
    return True, 64  # no decision within budget counts as conflicted


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=1000)
    parser.add_argument("--reps", type=int, default=10)
    parser.add_argument("--cohorts", type=int, default=64)
    parser.add_argument("--delivery-spread", type=int, default=1,
                        help="max extra rounds of per-(cohort, edge) delivery delay")
    parser.add_argument("--stagger", type=int, default=1,
                        help="max rounds of per-edge detection jitter")
    parser.add_argument("--delay-permille", type=int, default=1000,
                        help="probability (permille, per cohort-edge) of a nonzero "
                        "delivery delay: sub-round skew granularity (1000 = the "
                        "full uniform [0, spread] draw; one engine round is the "
                        "coarsest quantum, the paper's continuous-latency sim "
                        "sits below it)")
    parser.add_argument("--loss", type=float, default=0.0,
                        help="one-way loss fraction per non-primary cohort (paper sim: 0)")
    parser.add_argument(
        "--platform",
        default="cpu",
        help="jax platform (default cpu: the sweep is small, and the forced "
        "override avoids wedging on a dead accelerator tunnel; pass the "
        "accelerator platform explicitly to run there)",
    )
    args = parser.parse_args()

    from rapid_tpu.utils.platform import force_platform

    if not force_platform(args.platform):
        raise RuntimeError(
            f"could not force jax platform {args.platform!r} (a backend was "
            "already initialized); refusing to sweep on an unintended backend"
        )

    k = 10
    print(f"N={args.n}, K={k}, cohorts={args.cohorts}, reps={args.reps}")
    print(f"{'H':>3} {'L':>3} {'F':>4} {'conflict%':>10} {'avg rounds':>11}")
    for h in (9, 8, 7, 6):
        for l in (1, 2, 3, 4):
            if l >= h:
                continue
            for f in (2, 4, 8, 16):
                conflicts, rounds_sum = 0, 0
                for rep in range(args.reps):
                    conflict, rounds = run_once(
                        args.n, k, h, l, f, args.cohorts,
                        seed=hash((h, l, f, rep)) % 2**31,
                        delivery_spread=args.delivery_spread,
                        stagger=args.stagger,
                        loss=args.loss,
                        delay_permille=args.delay_permille,
                    )
                    conflicts += int(conflict)
                    rounds_sum += rounds
                print(
                    f"{h:>3} {l:>3} {f:>4} {100.0 * conflicts / args.reps:>9.1f}% "
                    f"{rounds_sum / args.reps:>11.1f}"
                )


if __name__ == "__main__":
    main()
