"""K/H/L sensitivity of almost-everywhere agreement (paper Fig. 11 analog).

The paper's experiment (§Evaluation, "K, H, L sensitivity study"): 1000
processes, F random failures; "We generate alert messages from the F
processes' observers and deliver these alerts to each process in a uniform
random order. We count the number of processes that announce a membership
proposal that did not include all F processes (a conflict)." — i.e. the
receivers differ ONLY in alert arrival ORDER, each order an independent
uniform permutation of the F*K alerts, and the conflict rate is the
FRACTION OF PROCESSES that announced early (a proposal missing >= 1 victim).

The engine reproduces that model BY DERIVATION, not tuning:

  * every (cohort, edge) delivery delay is an independent uniform draw in
    [0, spread] (hash streams, `_deliver_alerts`); as spread grows, the
    induced per-cohort arrival order converges to exactly the paper's
    independent uniform permutation (ties have probability 1/(spread+1)
    per pair and vanish);
  * all alerts fire simultaneously (stagger=0), matching "we generate
    alert messages from the F processes' observers" as one event;
  * the metric is the paper's: the fraction of receiver cohorts whose
    FIRST announced proposal misses >= 1 victim. (Each cohort is one
    sampled receiver state shared by ~N/C members.)

The only approximation is time discretization: simultaneous arrivals within
one round are tallied atomically, which can only HIDE an early announcement
(the batch is the favorable order), so measured rates approach the paper's
from below as --delivery-spread grows. Default 128 puts the per-pair tie
probability under 1%. No parameter is fitted to the paper's reported rates.

Usage: python examples/khl_sensitivity.py [--n 1000] [--reps 20] [--cohorts 64]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _detector_experiment_fn():
    """Build the jitted detector-only experiment (cached across cells).

    The paper's Fig. 11 study has NO consensus — it is a pure cut-detector
    experiment run until every receiver announces. Driving the full engine
    would let the cluster DECIDE (and apply the view change) long before
    slow receivers announce, truncating the sample; so this loop drives
    exactly the engine's delivery + cut-detection kernels
    (`_deliver_alerts` + `_cohort_cut_detection`, the same code the engine
    executes per round) and latches each cohort's FIRST announced proposal
    mask, entirely on device in one dispatch per run.
    """
    import functools

    import jax
    import jax.numpy as jnp

    from rapid_tpu.models.virtual_cluster import (
        _cohort_cut_detection,
        _deliver_alerts,
    )

    @functools.partial(jax.jit, static_argnums=(0, 3))
    def experiment(cfg, state, blocked_rows, budget):
        def cond(carry):
            _, _, got, t = carry
            return (~jnp.all(got)) & (t < budget)

        def body(carry):
            state, first_mask, got, t = carry
            new_bits = _deliver_alerts(cfg, state, state.fire_round, blocked_rows)
            heard_down = jnp.any((new_bits != 0) & state.alive[None, :], axis=1)
            (report_bits, released, announced, seen_down, proposed_now,
             prop_masks) = _cohort_cut_detection(cfg, state, new_bits, heard_down)
            state = state._replace(
                report_bits=report_bits, released=released,
                announced=announced, seen_down=seen_down,
                round_idx=state.round_idx + 1,
            )
            newly = proposed_now & ~got
            first_mask = jnp.where(newly[:, None], prop_masks, first_mask)
            return (state, first_mask, got | proposed_now, t + 1)

        init = (
            state,
            jnp.zeros((cfg.c, cfg.n), dtype=bool),
            jnp.zeros((cfg.c,), dtype=bool),
            jnp.int32(0),
        )
        _, first_mask, got, t = jax.lax.while_loop(cond, body, init)
        return first_mask, got, t

    return experiment


_EXPERIMENT = None


def run_once(n, k, h, l, f, cohorts, seed, delivery_spread=128, stagger=0,
             loss=0.0, delay_permille=1000) -> tuple:
    """One paper-experiment run.

    Returns (conflicted_cohorts, announced_cohorts, rounds_to_all_announced).
    A cohort is conflicted iff its first announced proposal differs from the
    full victim set (the paper's per-process conflict metric)."""
    global _EXPERIMENT
    import jax.numpy as jnp

    from rapid_tpu.models.virtual_cluster import VirtualCluster, _edge_masks

    if _EXPERIMENT is None:
        _EXPERIMENT = _detector_experiment_fn()

    rng = np.random.default_rng(seed)
    vc = VirtualCluster.create(
        n, k=k, h=h, l=l, cohorts=cohorts, fd_threshold=1, seed=seed,
        delivery_spread=delivery_spread, delivery_prob_permille=delay_permille,
    )
    cohort_of = rng.integers(0, cohorts, size=n).astype(np.int32)
    vc.assign_cohorts(cohort_of)
    if loss > 0:
        rx_block = np.zeros((cohorts, vc.cfg.n), dtype=bool)
        for c in range(1, cohorts):
            rx_block[c] = rng.random(vc.cfg.n) < loss
        vc.set_rx_block(rx_block)

    victims = rng.choice(n, size=f, replace=False)
    vc.crash(victims)
    # "We generate alert messages from the F processes' observers": fire all
    # victim edges as one event (stamped at the current round; optional
    # per-edge stagger delays firing like real detection jitter would).
    vc._stamp_fired_edges(jnp.asarray(victims), np.ones((f, k), dtype=bool))
    if stagger:
        # Spread fire rounds over [0, stagger] (delivery uses
        # round - fire_round). np.array, not asarray: jax buffers view as
        # read-only numpy.
        offs = rng.integers(0, stagger + 1, size=(f, k)).astype(np.int32)
        fire = np.array(vc.state.fire_round)
        fire[victims] = offs  # [f, k] rows for victim slots
        vc.state = vc.state._replace(fire_round=jnp.asarray(fire))

    _, blocked_rows = _edge_masks(vc.cfg, vc.state, vc.faults)
    budget = delivery_spread + stagger + 64
    first_mask, got, t = _EXPERIMENT(vc.cfg, vc.state, blocked_rows, budget)

    got = np.asarray(got)
    first_mask = np.asarray(first_mask)
    victims_mask = np.zeros(n, dtype=bool)
    victims_mask[victims] = True
    conflicted = int(
        (got & (first_mask[:, :n] != victims_mask[None, :]).any(axis=1)).sum()
    )
    return conflicted, int(got.sum()), int(t)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=1000)
    parser.add_argument("--reps", type=int, default=20,
                        help="paper: 20 repetitions per combination")
    parser.add_argument("--cohorts", type=int, default=64,
                        help="independent receiver states sampled per run")
    parser.add_argument("--delivery-spread", type=int, default=128,
                        help="uniform delay support per (cohort, edge); large "
                        "spread => per-cohort arrival order converges to the "
                        "paper's independent uniform permutation (see module "
                        "docstring — derived, not tuned)")
    parser.add_argument("--stagger", type=int, default=0,
                        help="max rounds of per-edge detection jitter (paper "
                        "model: 0 — alerts all generated at once)")
    parser.add_argument("--delay-permille", type=int, default=1000,
                        help="probability (permille, per cohort-edge) of a "
                        "nonzero delay — models milder-than-paper sub-round "
                        "skew; 1000 = the full uniform draw the paper model "
                        "derives to")
    parser.add_argument("--loss", type=float, default=0.0,
                        help="one-way loss fraction per non-primary cohort (paper sim: 0)")
    parser.add_argument(
        "--platform",
        default="cpu",
        help="jax platform (default cpu: the sweep is small, and the forced "
        "override avoids wedging on a dead accelerator tunnel; pass the "
        "accelerator platform explicitly to run there)",
    )
    args = parser.parse_args()

    from rapid_tpu.utils.platform import force_platform

    if not force_platform(args.platform):
        raise RuntimeError(
            f"could not force jax platform {args.platform!r} (a backend was "
            "already initialized); refusing to sweep on an unintended backend"
        )

    k = 10
    print(f"N={args.n}, K={k}, cohorts={args.cohorts}, reps={args.reps}, "
          f"spread={args.delivery_spread} (paper-permutation mode)")
    print(f"{'H':>3} {'L':>3} {'F':>4} {'conflict%':>10} {'silent%':>8} "
          f"{'avg rounds':>11}")
    for h in (9, 8, 7, 6):
        for l in (1, 2, 3, 4):
            if l >= h:
                continue
            for f in (2, 4, 8, 16):
                conflicted_total, announced_total, rounds_sum = 0, 0, 0
                total = args.cohorts * args.reps
                for rep in range(args.reps):
                    conflicted, announced, rounds = run_once(
                        args.n, k, h, l, f, args.cohorts,
                        seed=hash((h, l, f, rep)) % 2**31,
                        delivery_spread=args.delivery_spread,
                        stagger=args.stagger,
                        loss=args.loss,
                        delay_permille=args.delay_permille,
                    )
                    conflicted_total += conflicted
                    announced_total += announced
                    rounds_sum += rounds
                # Conflict rate over ANNOUNCED receivers; cohorts that never
                # announced (possible only under --loss, which can blind a
                # cohort below H forever) are surfaced as silent%, never
                # silently counted as conflict-free.
                print(
                    f"{h:>3} {l:>3} {f:>4} "
                    f"{100.0 * conflicted_total / max(announced_total, 1):>9.2f}% "
                    f"{100.0 * (total - announced_total) / total:>7.1f}% "
                    f"{rounds_sum / args.reps:>11.1f}"
                )


if __name__ == "__main__":
    main()
