"""K/H/L sensitivity of almost-everywhere agreement (paper Fig. 11 analog).

The reference paper measures, by simulation at N=1000 over 20 repetitions per
combination, how often the multi-node cut detector yields *conflicting*
proposals (different nodes proposing different cuts) for K=10,
H in {6..9}, L in {1..4}, F concurrent failures in {2,4,8,16}: ~2% conflicts
at H-L=5 with F=2, improving ~4x per extra watermark gap.

This reproduces the experiment on the TPU engine: F crashed members,
per-edge detection jitter (staggered failure detectors), and receiver cohorts
with randomized one-way delivery loss. A run conflicts when the fast round's
decision shows dissenting votes (total voters > max identical votes) or the
classic fallback had to fire.

Usage: python examples/khl_sensitivity.py [--n 1000] [--reps 10]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run_once(n, k, h, l, f, cohorts, seed) -> tuple:
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    rng = np.random.default_rng(seed)
    vc = VirtualCluster.create(
        n, k=k, h=h, l=l, cohorts=cohorts, fd_threshold=2, seed=seed
    )
    # Receivers split into cohorts; each non-primary cohort misses alerts from
    # a random 2% of sources (one-way loss).
    cohort_of = rng.integers(0, cohorts, size=n).astype(np.int32)
    vc.assign_cohorts(cohort_of)
    rx_block = np.zeros((cohorts, vc.cfg.n), dtype=bool)
    for c in range(1, cohorts):
        rx_block[c] = rng.random(vc.cfg.n) < 0.02
    vc.set_rx_block(rx_block)

    victims = rng.choice(n, size=f, replace=False)
    vc.crash(victims)
    vc.stagger_fd_counts(rng, spread_rounds=6)

    for round_idx in range(64):
        events = vc.step()
        if bool(events.decided):
            conflict = int(events.total_votes) > int(events.max_votes)
            return conflict, round_idx + 1
    return True, 64  # no decision within budget counts as conflicted


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=1000)
    parser.add_argument("--reps", type=int, default=10)
    parser.add_argument("--cohorts", type=int, default=4)
    args = parser.parse_args()

    k = 10
    print(f"N={args.n}, K={k}, cohorts={args.cohorts}, reps={args.reps}")
    print(f"{'H':>3} {'L':>3} {'F':>4} {'conflict%':>10} {'avg rounds':>11}")
    for h in (9, 8, 7, 6):
        for l in (1, 2, 3, 4):
            if l >= h:
                continue
            for f in (2, 4, 8, 16):
                conflicts, rounds_sum = 0, 0
                for rep in range(args.reps):
                    conflict, rounds = run_once(
                        args.n, k, h, l, f, args.cohorts, seed=hash((h, l, f, rep)) % 2**31
                    )
                    conflicts += int(conflict)
                    rounds_sum += rounds
                print(
                    f"{h:>3} {l:>3} {f:>4} {100.0 * conflicts / args.reps:>9.1f}% "
                    f"{rounds_sum / args.reps:>11.1f}"
                )


if __name__ == "__main__":
    main()
