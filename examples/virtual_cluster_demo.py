"""Demo: drive the TPU virtual-cluster engine through the BASELINE scenarios.

Runs (scaled to the attached accelerator):
  1. 1K virtual nodes, 1% crash-fault injection
  2. 10K virtual nodes, batched 512-node join wave
  3. 50K virtual nodes, asymmetric one-way partition
  4. 100K virtual nodes, 5% concurrent churn

Usage: python examples/virtual_cluster_demo.py [--small]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def timed(label, fn):
    start = time.perf_counter()
    result = fn()
    elapsed = (time.perf_counter() - start) * 1000
    print(f"  {label}: {elapsed:.1f} ms -> {result}")
    return result


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--small", action="store_true", help="scale down for quick runs")
    parser.add_argument(
        "--platform",
        default=None,
        help="force a jax platform (e.g. cpu). Default: the environment's "
        "accelerator — pass cpu explicitly when the accelerator tunnel is "
        "unavailable (jax.devices() hangs on a dead tunnel otherwise)",
    )
    args = parser.parse_args()
    scale = 10 if args.small else 1

    if args.platform:
        from rapid_tpu.utils.platform import force_platform

        if not force_platform(args.platform):
            raise RuntimeError(f"could not force jax platform {args.platform!r}")

    import jax
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    print(f"devices: {jax.devices()}")

    # 1. crash faults
    n = 1000 // scale * scale
    print(f"[1] N={n}, 1% crash")
    vc = VirtualCluster.create(n, fd_threshold=3, seed=0)
    victims = np.random.default_rng(0).choice(n, size=max(1, n // 100), replace=False)
    vc.crash(victims)
    vc.run_until_converged()  # warm-up compile included
    print(f"  converged: members {vc.membership_size}, epoch {vc.config_epoch}")

    # 2. join wave
    n = 10_000 // scale
    wave = 512 // scale
    print(f"[2] N={n}, {wave}-node join wave")
    vc = VirtualCluster.create(n, n_slots=n + wave, fd_threshold=3, seed=1)
    vc.inject_join_wave(list(range(n, n + wave)))
    rounds, _ = timed("join wave", lambda: vc.timed_convergence())
    print(f"  members {vc.membership_size}")

    # 3. asymmetric one-way partition
    n = 50_000 // scale
    print(f"[3] N={n}, one-way partition on 10 nodes")
    vc = VirtualCluster.create(n, fd_threshold=3, seed=2)
    faulty = list(range(100, 110))
    probe_fail = np.zeros((vc.cfg.n, vc.cfg.k), dtype=bool)
    probe_fail[faulty, :] = True  # all observers see these nodes as dead
    vc.set_flaky_edges(probe_fail)
    vc.run_until_converged()
    removed = ~vc.alive_mask[faulty]
    print(f"  removed exactly the faulty set: {removed.all()} "
          f"(members {vc.membership_size})")

    # 4. churn
    n = 100_000 // scale
    print(f"[4] N={n}, 5% churn")
    vc = VirtualCluster.create(n, n_slots=int(n * 1.05), fd_threshold=3, seed=3)
    rng = np.random.default_rng(3)
    crash = rng.choice(n, size=n // 20, replace=False)
    vc.crash(crash)
    vc.inject_join_wave(list(range(n, int(n * 1.05))))
    epochs = 0
    start = time.perf_counter()
    while epochs < 2:
        rounds, events = vc.run_until_converged(max_steps=32)
        if events is None:
            break
        epochs = vc.config_epoch
    elapsed = (time.perf_counter() - start) * 1000
    print(f"  churn settled in {elapsed:.1f} ms: members {vc.membership_size}, "
          f"epochs {vc.config_epoch}")


if __name__ == "__main__":
    main()
