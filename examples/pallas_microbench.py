"""Microbenchmark: the Pallas delivery kernel vs the engine's jnp path,
plus a per-convergence profile of the engine.

Answers VERDICT's "prove the Pallas kernel" ask with numbers: per-call
on-device latency of the engine's fused delivery pass on both paths at
engine-realistic shapes (the measurement that keeps the kernel honest —
round 2's equivalent run killed a slower watermark Mosaic kernel), the
XLA-fused watermark pass for the op-level record, and (with
``--profile DIR``) a TensorBoard/Perfetto trace of one full churn
convergence for the op-level breakdown.

Run on the accelerator (the Pallas path is TPU-gated; off-TPU this prints
the jnp numbers and notes the kernel was skipped):

    python examples/pallas_microbench.py [--platform tpu] [--profile /tmp/tr]

Timing discipline for tunnel backends: the dev tunnel adds ~69 ms RTT to
every device→host fetch, which swamps a millisecond-scale kernel if each
sample ends in its own fetch (``block_until_ready`` is advisory over the
tunnel, so a fetch is the only true barrier). Each sample therefore runs a
``lax.fori_loop`` chaining ITERS dependent kernel applications on device
(outputs fed back into inputs so nothing can be hoisted or elided) behind
ONE terminal scalar fetch, at two loop lengths; the reported per-call time
is the slope ``(t_hi − t_lo) / (iters_hi − iters_lo)``, which cancels the
constant RTT + dispatch + fetch term exactly. The constant itself is
reported as ``fetch_overhead_ms`` (≈ tunnel RTT when remote, ≈0 local).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

ITERS_LO, ITERS_HI = 2, 18


def timed(fn, reps: int = 10) -> float:
    """Min-of-reps wall ms per call; each call ends in a scalar fetch."""
    fn()  # warm (compile)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    return best


def slope_timed(make_chained) -> tuple[float, float]:
    """(per-iteration ms, constant-overhead ms) from two chained-loop lengths.

    ``make_chained(iters)`` must return a zero-arg callable that executes
    ``iters`` dependent kernel applications on device and ends in exactly
    one scalar fetch.
    """
    t_lo = timed(make_chained(ITERS_LO))
    t_hi = timed(make_chained(ITERS_HI))
    per_call = (t_hi - t_lo) / (ITERS_HI - ITERS_LO)
    overhead = max(t_lo - ITERS_LO * per_call, 0.0)
    return per_call, overhead


def speedup_of(jnp_ms: float, pallas_ms: float):
    """Ratio from the UNROUNDED slopes, or None when the measurement is too
    small/noisy to divide (a sub-resolution or negative slope — possible at
    tiny shapes now that the constant overhead no longer pads every
    sample)."""
    if jnp_ms <= 0.0 or pallas_ms <= 1e-6:
        return None
    return round(jnp_ms / pallas_ms, 2)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default=None,
                        help="force a jax platform (e.g. cpu); default: environment's")
    parser.add_argument("--n", type=int, default=1_000_000)
    parser.add_argument("--cohorts", type=int, default=8)
    parser.add_argument("--profile", default=None,
                        help="also trace one 100K-member churn convergence into DIR")
    args = parser.parse_args()

    if args.platform:
        from rapid_tpu.utils.platform import force_platform

        if not force_platform(args.platform):
            raise RuntimeError(
                f"could not force jax platform {args.platform!r} (a backend "
                "was already initialized); refusing to time the wrong backend"
            )

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rapid_tpu.ops.pallas_kernels import watermark_merge_classify

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    h, l, k = 9, 4, 10

    rng = np.random.default_rng(0)
    shape = (args.cohorts, args.n)
    old = jnp.asarray(rng.integers(0, 1 << k, size=shape, dtype=np.uint32))
    new = jnp.asarray(rng.integers(0, 1 << k, size=shape, dtype=np.uint32))
    mask = jnp.asarray(rng.random(shape) < 0.95)

    from functools import partial

    import jax.lax as lax

    def run_watermark():
        def make_chained(iters: int):
            @partial(jax.jit, static_argnums=(3,))
            def loop(old_b, new_b, mask_b, n_iter):
                def body(i, carry):
                    acc, cur = carry
                    bits, cls = watermark_merge_classify(
                        old_b, cur ^ i.astype(jnp.uint32), mask_b, h, l,
                    )
                    # Feed bits back as next iteration's input and fold the
                    # full classification into the accumulator: every element
                    # of both outputs is live, so XLA can neither elide the
                    # pass nor compute a slice of it.
                    return acc + jnp.sum(cls.astype(jnp.uint32)), bits

                acc, final = lax.fori_loop(
                    0, n_iter, body, (jnp.uint32(0), new_b))
                return acc + final[0, 0]

            return lambda: int(loop(old, new, mask, iters))

        return slope_timed(make_chained)

    # XLA-fused watermark pass: the jnp core IS the shipped path (a Mosaic
    # version measured 0.69x of this and was deleted); timed for the
    # op-level record and to notice any fusion regression.
    jnp_ms, jnp_ovh = run_watermark()
    results = {
        "watermark_shape": list(shape),
        "xla_fused_ms": round(jnp_ms, 3),
        "fetch_overhead_ms": round(jnp_ovh, 3),
    }
    print(json.dumps(results))

    # Delivery kernel: the fused (cohort-word x ring) pass vs the engine's
    # jnp loop, at engine-realistic shapes ([w*k, n] packed rx-block rows).
    from rapid_tpu.models.virtual_cluster import VirtualCluster, _deliver_alerts, _edge_masks

    def delivery_run(use_pallas: bool, n: int, c: int):
        vc = VirtualCluster.create(
            n, cohorts=c, fd_threshold=1, seed=1, use_pallas=use_pallas,
            delivery_spread=2,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash(np.asarray(rng.choice(n, size=max(1, n // 100), replace=False)))
        vc.step()  # compile + fire the detectors

        cfg, state, faults = vc.cfg, vc.state, vc.faults

        def make_chained(iters: int):
            @partial(jax.jit, static_argnums=(2,))
            def loop(state, faults, n_iter):
                _, blocked_rows = _edge_masks(cfg, state, faults)

                def body(i, acc):
                    # Each iteration's fire_round perturbation depends on the
                    # ACCUMULATED output of all previous iterations (acc % 2
                    # is unknowable before they execute), so the chain is a
                    # true data dependence — no unrolling/CSE can collapse
                    # it — and summing the output keeps every element live.
                    out = _deliver_alerts(
                        cfg, state,
                        state.fire_round - (acc % 2).astype(jnp.int32),
                        blocked_rows)
                    return acc + jnp.sum(out)

                return lax.fori_loop(0, n_iter, body, jnp.uint32(0))

            return lambda: int(loop(state, faults, iters))

        return slope_timed(make_chained)

    n_d, c_d = min(args.n, 100_000), 64
    d_jnp_ms, d_ovh = delivery_run(False, n_d, c_d)
    results_d = {
        "platform": platform,
        "delivery_shape": [c_d, n_d],
        "jnp_ms": round(d_jnp_ms, 3),
        "fetch_overhead_ms": round(d_ovh, 3),
    }
    if on_tpu:
        d_pallas_ms, _ = delivery_run(True, n_d, c_d)
        results_d["pallas_ms"] = round(d_pallas_ms, 3)
        results_d["speedup"] = speedup_of(d_jnp_ms, d_pallas_ms)
    else:
        results_d["pallas_ms"] = None
        results_d["note"] = "Mosaic delivery kernel is TPU-gated; re-run on the accelerator"
    print(json.dumps(results_d))

    if args.profile:
        from rapid_tpu.models.virtual_cluster import VirtualCluster
        from rapid_tpu.utils.profiling import trace

        n = 100_000

        def build_churn(seed: int):
            vc = VirtualCluster.create(
                n, n_slots=n + 2500, cohorts=64, fd_threshold=3, seed=seed,
                use_pallas=on_tpu, delivery_spread=2,
            )
            vc.assign_cohorts_roundrobin()
            vc.crash(np.random.default_rng(seed + 1).choice(n, size=2500, replace=False))
            vc.inject_join_wave(np.arange(n, n + 2500))
            vc.sync()
            return vc

        build_churn(0).run_to_decision(max_steps=96)  # warm/compile outside the trace
        vc2 = build_churn(1)
        with trace(args.profile):
            vc2.run_to_decision(max_steps=96)
        print(f"profile written to {args.profile}")


if __name__ == "__main__":
    main()
