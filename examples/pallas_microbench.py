"""Microbenchmark: the Pallas watermark kernel vs the jnp core, plus a
per-convergence profile of the engine.

Answers VERDICT's "prove the Pallas kernel" ask with numbers: cached-call
latency of ``watermark_merge_classify`` on both paths at engine-realistic
shapes, and (with ``--profile DIR``) a TensorBoard/Perfetto trace of one
full churn convergence for the op-level breakdown.

Run on the accelerator (the Pallas path is TPU-gated; off-TPU this prints
the jnp numbers and notes the kernel was skipped):

    python examples/pallas_microbench.py [--platform tpu] [--profile /tmp/tr]

Timing discipline for tunnel backends: ``block_until_ready`` is advisory, so
every sample is terminated by a scalar fetch that depends on the outputs.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def timed(fn, reps: int = 20) -> float:
    """Min-of-reps wall ms per call; each call ends in a scalar fetch."""
    fn()  # warm (compile)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    return best


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default=None,
                        help="force a jax platform (e.g. cpu); default: environment's")
    parser.add_argument("--n", type=int, default=1_000_000)
    parser.add_argument("--cohorts", type=int, default=8)
    parser.add_argument("--profile", default=None,
                        help="also trace one 100K-member churn convergence into DIR")
    args = parser.parse_args()

    if args.platform:
        from rapid_tpu.utils.platform import force_platform

        if not force_platform(args.platform):
            raise RuntimeError(
                f"could not force jax platform {args.platform!r} (a backend "
                "was already initialized); refusing to time the wrong backend"
            )

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rapid_tpu.ops.pallas_kernels import watermark_merge_classify

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    h, l, k = 9, 4, 10

    rng = np.random.default_rng(0)
    shape = (args.cohorts, args.n)
    old = jnp.asarray(rng.integers(0, 1 << k, size=shape, dtype=np.uint32))
    new = jnp.asarray(rng.integers(0, 1 << k, size=shape, dtype=np.uint32))
    mask = jnp.asarray(rng.random(shape) < 0.95)

    def run(use_pallas: bool):
        def call():
            bits, cls = watermark_merge_classify(old, new, mask, h, l, use_pallas=use_pallas)
            # ONE combined scalar fetch = the only true barrier on tunnel
            # backends (two fetches would double the per-sample RTT).
            return int(bits[0, 0] + cls[0, 0].astype(jnp.uint32))

        return timed(call)

    results = {
        "platform": platform,
        "shape": list(shape),
        "jnp_ms": round(run(False), 3),
    }
    if on_tpu:
        results["pallas_ms"] = round(run(True), 3)
        results["speedup"] = round(results["jnp_ms"] / results["pallas_ms"], 2)
    else:
        results["pallas_ms"] = None
        results["note"] = "Pallas path is TPU-gated; re-run on the accelerator"
    print(json.dumps(results))

    # Delivery kernel: the fused (cohort-word x ring) pass vs the engine's
    # jnp loop, at engine-realistic shapes ([w*k, n] packed rx-block rows).
    from rapid_tpu.models.virtual_cluster import VirtualCluster, _deliver_alerts, _edge_masks

    def delivery_run(use_pallas: bool, n: int, c: int) -> float:
        vc = VirtualCluster.create(
            n, cohorts=c, fd_threshold=1, seed=1, use_pallas=use_pallas,
            delivery_spread=2,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash(np.asarray(rng.choice(n, size=max(1, n // 100), replace=False)))
        vc.step()  # compile + fire the detectors

        cfg, state, faults = vc.cfg, vc.state, vc.faults

        @jax.jit
        def one_delivery(state, faults):
            _, blocked_rows = _edge_masks(cfg, state, faults)
            return _deliver_alerts(cfg, state, state.fire_round, blocked_rows)

        def call():
            return int(one_delivery(state, faults)[0, 0])

        return timed(call)

    n_d, c_d = min(args.n, 100_000), 64
    results_d = {
        "delivery_shape": [c_d, n_d],
        "jnp_ms": round(delivery_run(False, n_d, c_d), 3),
    }
    if on_tpu:
        results_d["pallas_ms"] = round(delivery_run(True, n_d, c_d), 3)
        results_d["speedup"] = round(results_d["jnp_ms"] / results_d["pallas_ms"], 2)
    else:
        results_d["pallas_ms"] = None
    print(json.dumps(results_d))

    if args.profile:
        from rapid_tpu.models.virtual_cluster import VirtualCluster
        from rapid_tpu.utils.profiling import trace

        n = 100_000

        def build_churn(seed: int):
            vc = VirtualCluster.create(
                n, n_slots=n + 2500, cohorts=64, fd_threshold=3, seed=seed,
                use_pallas=on_tpu, delivery_spread=2,
            )
            vc.assign_cohorts_roundrobin()
            vc.crash(np.random.default_rng(seed + 1).choice(n, size=2500, replace=False))
            vc.inject_join_wave(np.arange(n, n + 2500))
            vc.sync()
            return vc

        build_churn(0).run_to_decision(max_steps=96)  # warm/compile outside the trace
        vc2 = build_churn(1)
        with trace(args.profile):
            vc2.run_to_decision(max_steps=96)
        print(f"profile written to {args.profile}")


if __name__ == "__main__":
    main()
