"""Bootstrap benchmark: the paper's Fig. 5 / Table 1 scenario on the engine.

The reference paper's headline comparison is cluster BOOTSTRAP: N processes
join through a seed as fast as the protocol admits them (Rapid converges
2-2.32x faster than Memberlist and 3.23-5.81x faster than ZooKeeper at
N=2000, paper Fig. 5), and — Table 1 — does so through a handful of large
cuts: 4-10 unique intermediate cluster sizes where ZK/Memberlist pass
through ~N one-at-a-time sizes. The cleanliness comes from alert batching +
multi-node cut detection agreeing on whole join waves
(MembershipService.java:613-637, Cluster.java:406-437).

This script replays that scenario on the virtual-cluster engine: a small
seed cluster is up; the remaining members all request admission
concurrently, arriving in ``--waves`` batches (the engine analog of the
reference's 100 ms alert-batching windows slicing one thundering herd into
a few batched cuts); each batch is admitted through full consensus with
jittered per-cohort delivery. Reported per run:

  - wall_ms            end-to-end bootstrap time on this hardware
  - view_changes       consensus decisions taken (Table 1: O(waves), not O(N))
  - unique_sizes       every intermediate membership size observed
  - rounds             protocol rounds executed across all decisions

Usage:
    python examples/bootstrap_bench.py                  # N=2000, paper scale
    python examples/bootstrap_bench.py --n 100000       # TPU scale
    python examples/bootstrap_bench.py --waves 8 --seed-size 64
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def run_bootstrap(
    n_total: int,
    seed_size: int,
    waves: int,
    cohorts: int,
    delivery_spread: int,
    seed: int = 0,
    use_pallas: bool = False,
    max_steps: int = 64,
) -> dict:
    """Bootstrap seed_size -> n_total through `waves` batched join cuts."""
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    vc = VirtualCluster.create(
        seed_size,
        n_slots=n_total,
        cohorts=cohorts,
        fd_threshold=3,
        seed=seed,
        delivery_spread=delivery_spread,
        use_pallas=use_pallas,
    )
    vc.assign_cohorts_roundrobin()

    joiners = np.arange(seed_size, n_total)
    batches = np.array_split(joiners, waves)

    sizes = [vc.membership_size]
    total_rounds = 0
    view_changes = 0
    vc.sync()
    t0 = time.perf_counter()
    for batch in batches:
        if batch.size == 0:
            continue
        vc.inject_join_wave(batch)
        # One wave may land as one cut or (under delivery jitter) a couple;
        # keep deciding until every joiner in the batch is admitted.
        # run_to_decision's packed fetch already carries the membership, so
        # the loop condition reads sizes[-1] instead of paying a device
        # fetch (a full tunnel RTT) per check.
        target = sizes[-1] + batch.size
        # One device dispatch per WAVE (view changes applied on device; the
        # per-cut intermediate sizes — the paper Table 1 instrument — ride
        # back in the same fetch). Zero per-cut tunnel round trips.
        rounds, cuts, resolved, cut_sizes = vc.run_until_membership(
            target, max_steps=max_steps * 8, max_cuts=8
        )
        total_rounds += rounds
        if not resolved:
            raise RuntimeError(
                f"wave unresolved after {cuts} cuts / {rounds} rounds "
                f"(sizes {cut_sizes}, target {target})"
            )
        for size in cut_sizes:
            if size <= sizes[-1]:
                # Every decision in a pure join wave must admit someone; a
                # non-growing cut would corrupt the Table 1 instrument
                # (duplicate unique_sizes inflate cleanliness).
                raise RuntimeError(
                    f"decision did not grow membership ({sizes[-1]} -> {size})"
                )
            sizes.append(size)
        view_changes += cuts
    wall_ms = (time.perf_counter() - t0) * 1000.0

    if sizes[-1] != n_total:
        raise RuntimeError(f"bootstrap ended at {sizes[-1]} != {n_total}")
    return {
        "scenario": "bootstrap",
        "n_total": n_total,
        "seed_size": seed_size,
        "waves": waves,
        "wall_ms": round(wall_ms, 3),
        "view_changes": view_changes,
        "rounds": total_rounds,
        "unique_sizes": sizes,
        "cohorts": cohorts,
        "delivery_spread": delivery_spread,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default=None,
                        help="force a jax platform (e.g. cpu)")
    parser.add_argument("--n", type=int, default=2000,
                        help="total cluster size (paper Fig. 5 uses 2000)")
    parser.add_argument("--seed-size", type=int, default=64,
                        help="members already up before the herd arrives")
    parser.add_argument("--waves", type=int, default=8,
                        help="batching windows the joiner herd arrives in "
                             "(Table 1 reports 4-10 intermediate sizes)")
    parser.add_argument("--cohorts", type=int, default=16)
    parser.add_argument("--delivery-spread", type=int, default=2)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()

    if args.platform:
        from rapid_tpu.utils.platform import force_platform

        if not force_platform(args.platform):
            raise RuntimeError(f"could not force platform {args.platform!r}")

    import jax

    from rapid_tpu.ops.pallas_kernels import pallas_usable

    platform = jax.devices()[0].platform
    use_pallas = pallas_usable()

    # Warm the executables on a throwaway bootstrap, then measure.
    run_bootstrap(args.n, args.seed_size, args.waves, args.cohorts,
                  args.delivery_spread, seed=args.seed + 1,
                  use_pallas=use_pallas)
    result = run_bootstrap(args.n, args.seed_size, args.waves, args.cohorts,
                           args.delivery_spread, seed=args.seed,
                           use_pallas=use_pallas)
    result["platform"] = platform
    # Table 1's metric: intermediate sizes the cluster passed through —
    # O(waves) for Rapid vs ~N for ZK/Memberlist. The paper's wall-clock bar
    # (Memberlist ~95 s at N=2000) is a real-network number; the engine's
    # wall_ms shows the protocol itself is not the bottleneck.
    result["cleanliness"] = len(result["unique_sizes"])
    print(json.dumps(result))


if __name__ == "__main__":
    main()
