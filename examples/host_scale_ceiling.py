"""Locate the host protocol path's scale ceiling vs the device engine.

The framework ships two implementations of the same protocol: the asyncio
host path (one ``MembershipService`` per node — the reference architecture,
``ClusterTest.java``'s 50-node in-JVM regime) and the fused device engine
(``models/virtual_cluster.py``, one program for all N). The host path's cost
per view change is dominated by the O(N²) vote fan-out (every member
broadcasts its fast-round vote to every member) plus asyncio scheduling
overhead per message; the engine turns the same work into a handful of
batched array ops. This instrument measures WHERE the curves cross.

Method: for each N, wire N ``MembershipService`` instances directly on one
``InProcessNetwork`` (identical pre-built views — the convergence hot path,
without conflating O(N²)-per-join bootstrap cost), crash one member, and
pump a ``ManualClock`` until every service applies the view change. Wall
time measured around the pumping loop is pure host CPU cost (simulated time
never sleeps). The engine column runs the identical crash on a
``VirtualCluster`` of the same size and membership.

One JSON line per N:

    {"n": 200, "broadcast": "unicast", "host_crash_wall_ms": ...,
     "host_msgs": ..., "gossip_relays": ...,  # gossip mode only
     "engine_crash_wall_ms": ..., "sim_ms": ...}

Committed results live in EVALUATION.md ("Host-path scale ceiling"),
including the measured NEGATIVE result for --broadcast gossip (relay
duplication multiplies total deliveries for all-origin vote fan-outs).

    python examples/host_scale_ceiling.py [--sizes 50,100,200,350,500]
                                          [--broadcast unicast|gossip]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from rapid_tpu.utils.platform import force_platform

force_platform("cpu")

from rapid_tpu.messaging.inprocess import InProcessClient, InProcessNetwork, InProcessServer
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
from rapid_tpu.protocol.service import MembershipService
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint, NodeId
from rapid_tpu.utils.clock import ManualClock


async def host_crash_convergence(n: int, seed: int = 0, broadcast: str = "unicast"):
    """Wall-clock cost of one crash view-change across n host services.
    ``broadcast="gossip"`` swaps the O(N) unicast-to-all fan-out for the
    epidemic relay (ln-N fanout) at every node — same protocol, different
    egress shape."""
    if broadcast not in ("unicast", "gossip"):
        raise ValueError(f"broadcast must be 'unicast' or 'gossip', got {broadcast!r}")
    settings = Settings()  # reference defaults: 1 s FD, 100 ms batching
    endpoints = [Endpoint(f"10.20.{i // 250}.{i % 250}", 6000 + i) for i in range(n)]
    node_ids = [NodeId(0, i) for i in range(n)]
    network = InProcessNetwork()
    clock = ManualClock()
    fd = StaticFailureDetectorFactory()

    services = []
    servers = []
    for i in range(n):
        view = MembershipView(settings.k, node_ids=node_ids, endpoints=endpoints)
        client = InProcessClient(network, endpoints[i], settings)
        broadcaster = None
        if broadcast == "gossip":
            from rapid_tpu.messaging.gossip import GossipBroadcaster

            broadcaster = GossipBroadcaster(client, endpoints[i], rng=random.Random(seed + i))
        service = MembershipService(
            my_addr=endpoints[i],
            cut_detector=MultiNodeCutDetector(settings.k, settings.h, settings.l),
            view=view,
            settings=settings,
            client=client,
            fd_factory=fd,
            clock=clock,
            rng=random.Random(seed + i),
            node_id=node_ids[i],
            broadcaster=broadcaster,
        )
        server = InProcessServer(network, endpoints[i])
        server.set_membership_service(
            broadcaster.router(service) if broadcaster is not None else service
        )
        await server.start()
        await service.start()
        services.append(service)
        servers.append(server)

    victim = endpoints[n // 2]
    fd.add_failed_nodes([victim])
    network.blackholed.add(victim)
    live = [s for s in services if s.my_addr != victim]

    async def drain(rounds=40):
        for _ in range(rounds):
            await asyncio.sleep(0)

    t0 = time.perf_counter()
    sim_ms = 0.0
    while not all(s.membership_size == n - 1 for s in live):
        clock.advance_ms(50)
        sim_ms += 50
        await drain()
        if sim_ms > 120_000:
            raise TimeoutError(f"host n={n} did not converge in 120 s sim")
    wall_ms = (time.perf_counter() - t0) * 1000.0

    msgs = sum(s.metrics.counters.get("alerts_received", 0) for s in live)
    relays = sum(
        getattr(s.broadcaster, "relays_sent", 0) for s in services
    )
    for server in servers:
        await server.shutdown()
    for service in services:
        await service.shutdown()
    return wall_ms, sim_ms, msgs, relays


def engine_crash_convergence(n: int):
    """The same crash on the fused engine (current backend; CPU here)."""
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    endpoints = [Endpoint(f"10.20.{i // 250}.{i % 250}", 6000 + i) for i in range(n)]
    vc = VirtualCluster.from_endpoints(
        endpoints, n_slots=n, fd_threshold=1, delivery_spread=0
    )
    vc.crash([n // 2])
    vc.run_to_decision(max_steps=64)  # warm-up compile on first shape
    # Re-create for the measured run (state was consumed by the decision).
    vc = VirtualCluster.from_endpoints(
        endpoints, n_slots=n, fd_threshold=1, delivery_spread=0
    )
    vc.crash([n // 2])
    t0 = time.perf_counter()
    _, decided, _, _ = vc.run_to_decision(max_steps=64)
    wall_ms = (time.perf_counter() - t0) * 1000.0
    assert decided
    return wall_ms


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--sizes", default="50,100,200,350,500")
    parser.add_argument("--skip-engine", action="store_true")
    parser.add_argument("--broadcast", default="unicast", choices=["unicast", "gossip"])
    args = parser.parse_args()
    for n in (int(s) for s in args.sizes.split(",")):
        wall_ms, sim_ms, msgs, relays = asyncio.run(
            host_crash_convergence(n, broadcast=args.broadcast)
        )
        row = {
            "n": n,
            "broadcast": args.broadcast,
            "host_crash_wall_ms": round(wall_ms, 1),
            "host_msgs": msgs,
            "sim_ms": sim_ms,
        }
        if args.broadcast == "gossip":
            row["gossip_relays"] = relays
        if not args.skip_engine:
            row["engine_crash_wall_ms"] = round(engine_crash_convergence(n), 1)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
