"""Autotune the Pallas delivery kernel's lane-tile width per engine shape.

The delivery kernel tiles slots over lanes with a static tile width
(``EngineConfig.pallas_lanes``, default 128). At small N the width barely
matters; at N=1M the grid has N/width steps, so wider tiles amortize
per-step overhead — but too wide overflows VMEM or starves the pipeline.
Outputs are bit-identical across widths (the jitter hash is salted by the
GLOBAL slot index), so this is purely a latency knob.

This sweeps widths at the two headline shapes ([64, 100K] — the BASELINE
churn scenario — and [8, 1M] — the scale point) with the slope method from
pallas_microbench (two chained-loop lengths; cancels the constant
RTT/dispatch term exactly, which on the dev tunnel is ~69 ms and would
otherwise swamp a millisecond kernel). Prints one JSON line per shape with
the per-width slopes and the winner; run during a live TPU window:

    python examples/delivery_autotune.py [--widths 128,256,512,1024]

bench.py picks the winners up automatically from the committed
``evidence/*/autotune.jsonl`` (env overrides: RAPID_TPU_BENCH_LANES for
the main workload, RAPID_TPU_BENCH_LANES_1M for the 1M point).
"""

from __future__ import annotations

import argparse
import json
import sys
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--widths", default="128,256,512,1024")
    parser.add_argument("--platform", default=None)
    parser.add_argument("--interpret", action="store_true",
                        help="run the kernel in interpret mode (CPU smoke "
                        "of the sweep machinery; timings meaningless)")
    args = parser.parse_args()
    widths = [int(w) for w in args.widths.split(",")]

    if args.interpret and not args.platform:
        # Interpret smoke must not touch the accelerator: a wedged axon
        # tunnel hangs the first jax.devices() call forever.
        args.platform = "cpu"
    if args.platform:
        from rapid_tpu.utils.platform import force_platform

        if not force_platform(args.platform):
            raise RuntimeError(f"could not force platform {args.platform!r}")

    import jax
    import jax.lax as lax
    import jax.numpy as jnp
    import numpy as np

    from examples.pallas_microbench import slope_timed
    from rapid_tpu.models.virtual_cluster import VirtualCluster, _edge_masks
    from rapid_tpu.ops.pallas_kernels import delivery_new_bits_pallas

    platform = jax.devices()[0].platform
    if platform != "tpu" and not args.interpret:
        raise RuntimeError(
            "autotune needs the TPU (Mosaic path); pass --interpret for a "
            "CPU smoke of the machinery"
        )

    shapes = [(64, 100_000, 2), (8, 1_000_000, 2)]
    rng = np.random.default_rng(0)
    for c, n, spread in shapes:
        if args.interpret:
            n = min(n, 4_000)  # CPU interpret mode is slow; smoke only
        vc = VirtualCluster.create(
            n, cohorts=c, fd_threshold=1, seed=1, delivery_spread=spread,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash(np.asarray(rng.choice(n, size=max(1, n // 100), replace=False)))
        vc.step()  # fire the detectors
        cfg, state = vc.cfg, vc.state
        _, blocked_rows = _edge_masks(cfg, state, vc.faults)
        age_kn = state.round_idx - state.fire_round.T
        epoch = state.config_epoch.astype(jnp.uint32).reshape(1)

        result = {"platform": platform, "shape": [c, n], "spread": spread,
                  "per_width_ms": {}}
        baseline_out = None
        for width in widths:
            if not args.interpret:
                def make_chained(iters, width=width):
                    @partial(jax.jit, static_argnums=(2,))
                    def loop(blocked, age, n_iter):
                        def body(i, acc):
                            out = delivery_new_bits_pallas(
                                blocked,
                                age - (acc % 2).astype(jnp.int32),
                                epoch, cfg.k, spread, 1000,
                                lanes=width,
                            )
                            return acc + jnp.sum(out)

                        return lax.fori_loop(0, n_iter, body, jnp.uint32(0))

                    return lambda: int(loop(blocked_rows, age_kn, iters))

                per_call, _ = slope_timed(make_chained)
                result["per_width_ms"][str(width)] = round(per_call, 4)
            # Cross-width equivalence (the bit-identical claim). In
            # --interpret smoke mode this is the whole test: slope-timing
            # interpreted Mosaic would take minutes per width for numbers
            # that mean nothing.
            out = delivery_new_bits_pallas(
                blocked_rows, age_kn, epoch, cfg.k, spread, 1000,
                interpret=args.interpret, lanes=width,
            )
            if baseline_out is None:
                baseline_out = np.asarray(out)  # fetch ONCE ([32, n] uint32)
            else:
                np.testing.assert_array_equal(np.asarray(out), baseline_out)
        if result["per_width_ms"]:
            best = min(result["per_width_ms"], key=result["per_width_ms"].get)
            result["best_width"] = int(best)
        else:
            result["best_width"] = None
            result["note"] = "interpret smoke: equivalence only, no timing"
        print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
