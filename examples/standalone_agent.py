"""Standalone cluster agent over the TCP transport.

CLI parity with the reference's example agents
(examples/src/main/java/com/vrg/standalone/StandaloneAgent.java:92-110 and
AgentWithNettyMessaging.java): the seed starts a cluster, everyone else joins
it; three subscriptions log view changes; membership size prints every second.

Run a 3-node cluster on localhost:

    python examples/standalone_agent.py --listen-address 127.0.0.1:9001 \
        --seed-address 127.0.0.1:9001 &
    python examples/standalone_agent.py --listen-address 127.0.0.1:9002 \
        --seed-address 127.0.0.1:9001 &
    python examples/standalone_agent.py --listen-address 127.0.0.1:9003 \
        --seed-address 127.0.0.1:9001 &
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from rapid_tpu.messaging.tcp import TcpClient, TcpServer
from rapid_tpu.messaging.udp import UdpHybridClient, UdpHybridServer
from rapid_tpu.monitoring.windowed import WindowedFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.protocol.events import ClusterEvents
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint
from rapid_tpu.utils import exposition

LOG = logging.getLogger("standalone_agent")


def subscription_logger(event: ClusterEvents):
    def callback(change):
        LOG.info(
            "%s: config %d, %d members, delta: %s",
            event.name,
            change.configuration_id,
            len(change.membership),
            [(str(sc.endpoint), sc.status.name) for sc in change.status_changes],
        )

    return callback


async def run(args) -> None:
    listen = Endpoint.parse(args.listen_address)
    seed = Endpoint.parse(args.seed_address)
    settings = Settings()
    metadata = (("role", args.role.encode()),) if args.role else ()
    if args.transport == "udp":
        # Hybrid: joins/probes over TCP, alerts/votes as datagrams.
        client, server = UdpHybridClient(listen, settings), UdpHybridServer(listen)
    else:
        client, server = TcpClient(listen, settings), TcpServer(listen)

    fd_factory = None  # default: ping-pong consecutive-failure counter
    if args.fd == "windowed":
        # The paper's stated policy: >=40% of the last 10 probes failed.
        fd_factory = WindowedFailureDetectorFactory(listen, client)

    broadcaster_factory = None  # default: unicast-to-all
    if args.broadcast == "gossip":
        # Epidemic relay: per-node egress O(log N) instead of origin O(N).
        from rapid_tpu.messaging.gossip import GossipBroadcaster

        broadcaster_factory = GossipBroadcaster.factory()

    if listen == seed:
        LOG.info("starting cluster as seed at %s", listen)
        cluster = await Cluster.start(
            listen, settings=settings, client=client, server=server,
            metadata=metadata, fd_factory=fd_factory,
            broadcaster_factory=broadcaster_factory,
        )
    else:
        LOG.info("joining cluster at %s from %s", seed, listen)
        cluster = await Cluster.join(
            seed, listen, settings=settings, client=client, server=server,
            metadata=metadata, fd_factory=fd_factory,
            broadcaster_factory=broadcaster_factory,
        )

    for event in (
        ClusterEvents.VIEW_CHANGE_PROPOSAL,
        ClusterEvents.VIEW_CHANGE,
        ClusterEvents.KICKED,
    ):
        cluster.register_subscription(event, subscription_logger(event))

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)

    def dump_metrics() -> None:
        # The unified telemetry snapshot (utils/exposition.py schema):
        # metrics + transport accounting + the full flight recording — one
        # file per node, the exact input tools/traceview.py merges. Written
        # atomically so a concurrently-running traceview never reads a
        # torn JSON.
        tmp = args.metrics_dump + ".tmp"
        with open(tmp, "w") as f:
            f.write(exposition.snapshot_json(cluster.telemetry_snapshot(), indent=2))
            f.write("\n")
        os.replace(tmp, args.metrics_dump)

    def print_health() -> None:
        # One JSON line per report interval: the node's health state
        # (utils/health.py vocabulary) and its phase-decomposed convergence
        # quantiles — the machine-readable heartbeat a wrapper script or CI
        # probe consumes without parsing the full --metrics-dump snapshot.
        snap = cluster.telemetry_snapshot(recorder_tail=0)
        family = snap["metrics"].get("view_change_phase_ms") or {}
        print(json.dumps({
            "node": snap["node"],
            "health": snap["health"],
            "configuration_id": snap["configuration_id"],
            "membership_size": snap["membership_size"],
            "phases": {
                phase: {k: hist[k] for k in ("count", "p50", "p90", "p99", "max")}
                for phase, hist in family.items()
            },
        }), flush=True)

    async def reporter():
        while not stop.is_set():
            LOG.info("membership size: %d (config %d)",
                     cluster.membership_size, cluster.service.view.configuration_id)
            if args.metrics_dump:
                dump_metrics()
            if args.health:
                print_health()
            await asyncio.sleep(args.report_interval)

    reporter_task = asyncio.ensure_future(reporter())
    await stop.wait()
    reporter_task.cancel()
    if args.metrics_dump:
        dump_metrics()  # final recording survives the shutdown
    LOG.info("leaving gracefully")
    await cluster.leave_gracefully()


def main() -> None:
    parser = argparse.ArgumentParser(description="rapid_tpu standalone agent")
    parser.add_argument("--listen-address", required=True, help="host:port to listen on")
    parser.add_argument("--seed-address", required=True,
                        help="host:port of the seed (same as listen-address to bootstrap)")
    parser.add_argument("--role", default="", help="role metadata tag shared with the cluster")
    parser.add_argument("--transport", choices=("tcp", "udp"), default="tcp",
                        help="tcp: everything over TCP; udp: hybrid with datagram alerts/votes")
    parser.add_argument("--fd", choices=("pingpong", "windowed"), default="pingpong",
                        help="failure-detection policy: pingpong = consecutive-failure "
                        "counter (the reference code's); windowed = fraction of the "
                        "last-N probes (the paper's)")
    parser.add_argument("--broadcast", choices=("unicast", "gossip"), default="unicast",
                        help="broadcast strategy: unicast-to-all (the reference's "
                        "default) or epidemic gossip relay (the alternate "
                        "IBroadcaster impl its docs name)")
    parser.add_argument("--report-interval", type=float, default=1.0)
    parser.add_argument("--health", action="store_true",
                        help="print the node's health state and phase-decomposed "
                        "convergence quantiles as one JSON line per report "
                        "interval (machine-readable; see utils/health.py for "
                        "the state vocabulary)")
    parser.add_argument("--metrics-dump", default="", metavar="PATH",
                        help="write the node's unified telemetry snapshot "
                        "(metrics, transport stats, flight recording) to PATH "
                        "as JSON every report interval and on shutdown; feed "
                        "one file per node to tools/traceview.py to merge a "
                        "cluster-wide timeline")
    args = parser.parse_args()
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )
    asyncio.run(run(args))


if __name__ == "__main__":
    main()
