"""Measure the hybrid TCP+UDP transport's datagram-loss cost curve.

For each loss rate, brings up a cluster over the hybrid transport with
seeded datagram loss injected at the sender (messaging.udp.LossyDatagramClient
— the post-commit drop point where real network loss strikes), drives the
same churn scenario (join wave, then a crash), and reports convergence
wall-clock plus the forced-rejoin count (service metric
``decision_missing_joiner_uuid`` — the transport's admitted failure mode,
messaging/udp.py docstring). One JSON line per point:

    {"loss_pct": 10, "join_wave_ms": ..., "crash_ms": ..., "forced_rejoins": 0,
     "kicked": 0, "datagrams_dropped": ..., "datagrams_delivered": ...}

Committed results live in EVALUATION.md ("Datagram loss tradeoff").

    python examples/udp_loss_curve.py [--rates 0,1,5,10,20] [--nodes 8] [--seed 42]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import socket
import time

from rapid_tpu.messaging.udp import LossyDatagramClient, UdpHybridServer
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint


def _settings() -> Settings:
    s = Settings()
    s.batching_window_ms = 20
    s.failure_detector_interval_ms = 50
    s.rpc_timeout_ms = 500
    s.rpc_join_timeout_ms = 4000
    s.rpc_probe_timeout_ms = 200
    s.consensus_fallback_base_delay_ms = 1000
    s.join_attempts = 10
    return s


def _free_ports(count: int) -> list:
    """Kernel-assigned free ports, reserved briefly then released: avoids
    collisions with anything else running on the host."""
    socks, ports = [], []
    for _ in range(count):
        sk = socket.socket()
        sk.bind(("127.0.0.1", 0))
        socks.append(sk)
        ports.append(sk.getsockname()[1])
    for sk in socks:
        sk.close()
    return ports


async def measure(loss_rate: float, n_nodes: int, seed: int) -> dict:
    settings = _settings()
    fd = StaticFailureDetectorFactory()
    rng = random.Random(seed)
    ports = _free_ports(n_nodes)
    eps = [Endpoint("127.0.0.1", p) for p in ports]
    clients = {}

    def client(i: int) -> LossyDatagramClient:
        c = LossyDatagramClient(
            eps[i], settings, loss_rate=loss_rate,
            rng=random.Random(rng.randrange(1 << 30)),
        )
        clients[i] = c
        return c

    n_seed = n_nodes - 3
    clusters = [
        await Cluster.start(eps[0], settings=settings, client=client(0),
                            server=UdpHybridServer(eps[0]), fd_factory=fd,
                            rng=random.Random(seed))
    ]
    for i in range(1, n_seed):
        clusters.append(
            await Cluster.join(eps[0], eps[i], settings=settings, client=client(i),
                               server=UdpHybridServer(eps[i]), fd_factory=fd,
                               rng=random.Random(seed + i))
        )

    async def converged(size: int, members) -> float:
        t0 = time.perf_counter()
        while not all(c.membership_size == size for c in members):
            await asyncio.sleep(0.02)
            if time.perf_counter() - t0 > 120:
                raise TimeoutError(f"no convergence to {size}")
        return (time.perf_counter() - t0) * 1000.0

    await converged(n_seed, clusters)

    # Join wave: 3 concurrent joiners (UP alerts + votes on lossy datagrams).
    t0 = time.perf_counter()
    joiners = await asyncio.gather(*(
        Cluster.join(eps[0], eps[i], settings=settings, client=client(i),
                     server=UdpHybridServer(eps[i]), fd_factory=fd,
                     rng=random.Random(seed + i))
        for i in range(n_seed, n_nodes)
    ))
    clusters.extend(joiners)
    await converged(n_nodes, clusters)
    join_wave_ms = (time.perf_counter() - t0) * 1000.0

    # Crash (DOWN alerts on lossy datagrams).
    victim = clusters[2]
    await victim.shutdown()
    fd.add_failed_nodes([victim.listen_address])
    survivors = [c for c in clusters if c is not victim]
    t0 = time.perf_counter()
    await converged(n_nodes - 1, survivors)
    crash_ms = (time.perf_counter() - t0) * 1000.0

    result = {
        "loss_pct": round(loss_rate * 100, 1),
        "n_nodes": n_nodes,
        "join_wave_ms": round(join_wave_ms, 1),
        "crash_ms": round(crash_ms, 1),
        "forced_rejoins": sum(
            c.service.metrics.counters["decision_missing_joiner_uuid"] for c in survivors
        ),
        "kicked": sum(c.service.metrics.counters["kicked"] for c in survivors),
        "datagrams_dropped": sum(c.datagrams_dropped for c in clients.values()),
        "datagrams_delivered": sum(c.datagrams_delivered for c in clients.values()),
    }
    await asyncio.gather(*(c.shutdown() for c in survivors), return_exceptions=True)
    return result


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--rates", default="0,1,5,10,20",
                        help="comma-separated loss percentages")
    parser.add_argument("--nodes", type=int, default=8)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()
    for pct in (float(r) for r in args.rates.split(",")):
        result = asyncio.run(measure(pct / 100.0, args.nodes, args.seed))
        print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
