"""Benchmark: view-change convergence wall-clock for the TPU virtual-cluster
engine.

Scenario (BASELINE.json config 4 / BASELINE.md targets table, bottom row):
N = 100K virtual members with **5% churn** — a simultaneous join wave and
crash set — under contested conditions: 64 independently-jittered receiver
cohorts (delivery-delay skew + staggered failure detectors), the implicit-
invalidation pass live (joins in flight while DOWN alerts spread), and two
racing classic-fallback coordinators armed. Measured: wall-clock from fault
injection to the cluster converging on the final membership (every churn
event resolved through consensus — one combined UP+DOWN cut, or two
sequential cuts, depending on how the jittered deliveries interleave).
Target: < 500 ms on one TPU v5e chip. The same scenario also runs at the
1M-member point (1% crash) by default.

The scenario is deliberately hard enough that the CPU fallback cannot hide
behind it: per round it does O(C·N·K) delivery work that the TPU's VPU chews
through in microseconds.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_PROBE_ATTEMPTS = 2
_PROBE_TIMEOUT_S = 150


def _env_flag(name: str) -> bool:
    """Truthy env flag: unset, empty, '0', and 'false' all mean OFF."""
    return os.environ.get(name, "").lower() not in ("", "0", "false")


def _probe_backend_once() -> tuple:
    """(ok, detail): init devices in a subprocess with a timeout."""
    detail = "probe timed out"
    # Manual poll loop instead of subprocess.run: run()'s TimeoutExpired path
    # does kill()+wait() with no bound, and a child wedged in an
    # uninterruptible driver call (the exact failure this guards against)
    # survives SIGKILL — the reap must be abandonable.
    probe = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )
    deadline = time.monotonic() + _PROBE_TIMEOUT_S
    while time.monotonic() < deadline:
        code = probe.poll()
        if code is not None:
            if code == 0:
                return True, ""
            # Surface the real diagnostic: a nonzero exit is a misconfigured
            # backend (missing/broken driver), not a wedge.
            try:
                detail = (probe.stderr.read() or b"").decode(errors="replace")[-800:]
            except Exception:  # noqa: BLE001 — diagnostics are best-effort
                pass
            return False, detail
        time.sleep(1)
    probe.kill()
    try:
        probe.wait(timeout=5)
    except subprocess.TimeoutExpired:
        pass  # unreapable (D-state) child: abandon it, fall back anyway
    return False, detail


def _ensure_responsive_backend() -> None:
    """The axon tunnel backend can wedge such that ``jax.devices()`` blocks
    forever (observed after killed mid-device-operation sessions). Probe
    device init in a subprocess with a timeout, RETRYING once (transient
    tunnel hiccups recover between attempts); only if every attempt hangs or
    fails, re-exec on CPU so the bench always emits its JSON line instead of
    hanging the driver. Skip with RAPID_TPU_BENCH_NO_PROBE=1."""
    if _env_flag("RAPID_TPU_BENCH_NO_PROBE") or os.environ.get("JAX_PLATFORMS") == "cpu":
        return
    detail = ""
    for attempt in range(_PROBE_ATTEMPTS):
        ok, detail = _probe_backend_once()
        if ok:
            return
        print(
            f"bench: accelerator probe attempt {attempt + 1}/{_PROBE_ATTEMPTS} "
            f"failed ({detail or 'hang'})",
            file=sys.stderr,
        )
        if attempt + 1 < _PROBE_ATTEMPTS:
            time.sleep(15)
    print(
        "bench: accelerator backend unresponsive after "
        f"{_PROBE_ATTEMPTS} attempts; falling back to CPU",
        file=sys.stderr,
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RAPID_TPU_BENCH_NO_PROBE"] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def main() -> None:
    _ensure_responsive_backend()
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # sitecustomize imported jax before us; env alone is too late — and
        # the axon plugin initializes its backend even under
        # JAX_PLATFORMS=cpu unless the live config is overridden.
        from rapid_tpu.utils.platform import force_platform

        force_platform("cpu")
    import numpy as np

    from rapid_tpu.utils._native import ensure_built

    ensure_built()  # compile the native host library outside any event loop

    from rapid_tpu.models.virtual_cluster import VirtualCluster

    n = 100_000
    churn_frac = 0.05  # BASELINE config 4: 5% churn (half joins, half crashes)
    n_join = int(n * churn_frac / 2)
    n_crash = int(n * churn_frac / 2)
    fd_threshold = 3
    k_rings = 10
    cohorts = 64
    delivery_spread = 2
    baseline_target_ms = 500.0
    max_view_changes = 4  # churn resolves in >=2 cuts; allow stragglers

    platform = jax.devices()[0].platform

    # The Mosaic kernel path is strictly an optimization: smoke-test it once
    # (pallas_usable) and drop to the bit-identical jnp core if it fails,
    # rather than dying mid-benchmark on the accelerator.
    from rapid_tpu.ops.pallas_kernels import pallas_usable

    use_pallas = pallas_usable()
    if platform == "tpu" and not use_pallas:
        print("bench: pallas kernel unusable; using jnp core", file=sys.stderr)

    def build(seed: int):
        vc = VirtualCluster.create(
            n,
            n_slots=n + n_join,
            k=k_rings,
            h=9,
            l=4,
            cohorts=cohorts,
            fd_threshold=fd_threshold,
            seed=seed,
            use_pallas=use_pallas,
            delivery_spread=delivery_spread,
            concurrent_coordinators=2,
        )
        vc.assign_cohorts_roundrobin()
        rng = np.random.default_rng(seed + 1000)
        vc.stagger_fd_counts(rng, spread_rounds=3)
        victims = rng.choice(n, size=n_crash, replace=False)
        joiners = np.arange(n, n + n_join)
        vc.crash(victims)
        vc.inject_join_wave(joiners)
        return vc, victims

    def resolve_churn(vc) -> int:
        """Run single-dispatch convergences until the churn is fully
        resolved; returns the number of committed view changes. One packed
        scalar fetch per cut (membership rides along — no extra RTT)."""
        cuts = 0
        members = -1
        for _ in range(max_view_changes):
            _, decided, _, members = vc.run_to_decision(max_steps=96)
            assert decided, "engine did not converge"
            cuts += 1
            if members == n:  # joins in, crashes out
                return cuts
        raise AssertionError(
            f"churn unresolved after {max_view_changes} view changes "
            f"(membership {members})"
        )

    # Warm-up: compile every branch the timed run takes (convergence loop,
    # view-change application, second-cut re-entry).
    vc, _ = build(seed=0)
    vc.sync()
    resolve_churn(vc)

    # Timed runs on fresh state (same shapes -> cached executables).
    samples = []
    cuts_per_sample = []
    for rep in range(3):
        vc, victims = build(seed=rep)
        # Real barrier: state upload/init must complete before the clock
        # starts (block_until_ready is advisory on tunnel backends).
        vc.sync()
        start = time.perf_counter()
        cuts = resolve_churn(vc)
        elapsed_ms = (time.perf_counter() - start) * 1000.0
        # resolve_churn's membership_size reads are scalar fetches — the
        # clock stops after real device completion.
        assert vc.membership_size == n
        assert not vc.alive_mask[victims].any()
        assert vc.alive_mask[n : n + n_join].all()
        samples.append(elapsed_ms)
        cuts_per_sample.append(cuts)

    # Fixed device<->host round-trip latency of this environment (the axon
    # tunnel); a co-located deployment would not pay it.
    import jax.numpy as jnp

    probe = jax.jit(lambda a: a + 1)
    int(probe(jnp.int32(1)))
    t0 = time.perf_counter()
    int(probe(jnp.int32(2)))
    rtt_ms = (time.perf_counter() - t0) * 1000.0

    # The 1M-member point (1% crash, 8 cohorts), on by default on the
    # accelerator per the BASELINE scale story. On the CPU fallback it is
    # skipped (a 1M-member CPU run adds many minutes for a number that only
    # matters on the accelerator — the fallback must still emit its JSON
    # line within the driver's budget); RAPID_TPU_BENCH_XL=1 forces it,
    # RAPID_TPU_BENCH_NO_XL=1 suppresses it everywhere.
    xl_ms = None
    run_xl = (platform == "tpu") or _env_flag("RAPID_TPU_BENCH_XL")
    if run_xl and not _env_flag("RAPID_TPU_BENCH_NO_XL"):
        n_xl = 1_000_000

        def build_xl(seed: int):
            vcx = VirtualCluster.create(
                n_xl,
                k=10,
                h=9,
                l=4,
                cohorts=8,
                fd_threshold=fd_threshold,
                seed=seed,
                use_pallas=use_pallas,
                delivery_spread=delivery_spread,
            )
            vcx.assign_cohorts_roundrobin()
            vcx.crash(
                np.random.default_rng(seed).choice(n_xl, size=n_xl // 100, replace=False)
            )
            return vcx

        vcx = build_xl(7)
        vcx.sync()
        vcx.run_to_decision(max_steps=96)  # warm-up/compile
        vcx = build_xl(8)
        vcx.sync()
        t0 = time.perf_counter()
        _, decided_xl, _, _ = vcx.run_to_decision(max_steps=96)
        xl_ms = (time.perf_counter() - t0) * 1000.0
        assert decided_xl and vcx.membership_size == n_xl - n_xl // 100

    value = min(samples)
    print(
        json.dumps(
            {
                "metric": f"churn_resolution_ms_n{n}_churn{int(churn_frac * 100)}pct",
                "value": round(value, 3),
                "unit": "ms",
                "vs_baseline": round(baseline_target_ms / value, 3),
                "platform": platform,
                "samples_ms": [round(s, 3) for s in samples],
                "view_changes": cuts_per_sample,
                "n_members": n,
                "joins": n_join,
                "crashes": n_crash,
                "cohorts": cohorts,
                "delivery_spread": delivery_spread,
                # Logical alert deliveries during convergence: every fired
                # edge alert (faults x K rings) reaches all N receivers —
                # the BASELINE's alerts/sec axis.
                "alert_deliveries_per_sec": round(
                    (n_crash + n_join) * k_rings * n / (value / 1000.0), 0
                ),
                "device_rtt_ms": round(rtt_ms, 3),
                **({"n1M_crash1pct_ms": round(xl_ms, 3)} if xl_ms is not None else {}),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
