"""Benchmark: view-change convergence wall-clock for the TPU virtual-cluster
engine.

Scenario (BASELINE.json config 4 / BASELINE.md targets table, bottom row):
N = 100K virtual members with **5% churn** — a simultaneous join wave and
crash set — under contested conditions: 64 independently-jittered receiver
cohorts (delivery-delay skew + staggered failure detectors), the implicit-
invalidation pass live (joins in flight while DOWN alerts spread), and two
racing classic-fallback coordinators armed. Measured: wall-clock from fault
injection to the cluster converging on the final membership (every churn
event resolved through consensus — one combined UP+DOWN cut, or two
sequential cuts, depending on how the jittered deliveries interleave).
Target: < 500 ms on one TPU v5e chip.

The HEADLINE scale number is ``n1M_crash1pct_ms``: 1M members, 1% crash,
one single-dispatch convergence (ROADMAP item 1 promoted it from side
metric to first-class). It has its own ledger stage (``xl_point``), its own
watchdog budget, and device-memory telemetry recorded alongside — and it is
never silently absent: the emitted JSON always carries the measured value
or an explicit ``n1M_status`` marker (a CPU run exercises the full stage
path at a ramped-down N; snapshot replays carry the captured value under
the usual snapshot/stale flags). ``RAPID_TPU_BENCH_STRETCH=10M`` opts into
the 10M stretch point (``stretch_point`` stage, ``n10M_crash1pct_ms``).

The scenario is deliberately hard enough that the CPU fallback cannot hide
behind it: per round it does O(C·N·K) delivery work that the TPU's VPU chews
through in microseconds.

Execution structure: the accelerator attempt runs in a WATCHDOGGED CHILD
process. The axon tunnel backend can wedge such that any device call blocks
forever and the wedged process survives SIGKILL (observed whenever a client
is killed mid-device-operation); running the whole attempt in a child whose
liveness is judged by its progress marks means the bench always terminates.

Observability: every run appends an append-only JSONL ledger
(rapid_tpu/utils/ledger.py; ``--ledger PATH``, default ``bench_ledger.jsonl``)
— run/attempt/stage begin+end events with durations and per-stage timeouts,
compile/persistent-cache stats and device memory from the engine-telemetry
tier, heartbeat gaps, and provenance (git rev + code hash over the
measurement paths) — so every number in the trajectory is attributable and a
wedged run points at exactly the stage it died in (render with
``tools/perfview.py``). Failure is LOUD: a wedged accelerator exits nonzero;
replaying a committed TPU snapshot requires the explicit ``--allow-snapshot``
flag (or RAPID_TPU_BENCH_ALLOW_SNAPSHOT=1) and is always marked in the
ledger, and the legacy CPU re-run requires ``--cpu-fallback`` (or
RAPID_TPU_BENCH_CPU_FALLBACK=1). On success the bench emits its ONE JSON
line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

_START = time.monotonic()


def _env_flag(name: str) -> bool:
    """Truthy env flag: unset, empty, '0', and 'false' all mean OFF."""
    return os.environ.get(name, "").lower() not in ("", "0", "false")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _autotuned_lanes(n: int, env_name: str, default: int = 128) -> int:
    """Delivery-kernel tile width for an N-slot shape: the caller's env
    override if set (RAPID_TPU_BENCH_LANES for the main workload at any N —
    the capture sweep plumbs per-point widths through it — and
    RAPID_TPU_BENCH_LANES_1M for the separate XL point), else the best
    width from the newest committed autotune evidence
    (evidence/*/autotune.jsonl by mtime, written on hardware by
    examples/delivery_autotune.py) for the nearest measured shape — so a
    driver-invoked live run benefits from captured tuning with no env
    plumbing. Falls back to the default width on any gap."""
    if os.environ.get(env_name, ""):
        return _env_int(env_name, default)
    root = os.path.dirname(os.path.abspath(__file__))
    paths = glob.glob(os.path.join(root, "evidence", "*", "autotune.jsonl"))
    try:
        paths.sort(key=os.path.getmtime)  # oldest first; newest overwrites
    except OSError:
        paths.sort()
    best: dict = {}
    for path in paths:
        try:
            lines = open(path).read().splitlines()
        except OSError:
            continue
        for line in lines:
            try:
                d = json.loads(line)
                width = d.get("best_width")
                # Trust only sane hardware-measured widths.
                if d.get("platform") == "tpu" and width in (128, 256, 512, 1024):
                    best[d["shape"][1]] = width
            except (json.JSONDecodeError, KeyError, IndexError, TypeError):
                continue  # one bad line never poisons the rest
    # Only inherit a tuned width from a comparable shape: a 2K smoke run
    # must not pick up the 100K-tuned width (1024 lanes on a 2K-slot array
    # is pad-dominated). Within 4x of a measured N the tiling economics
    # carry over; among eligible shapes the closest by RATIO wins (absolute
    # distance would bias toward the largest measured shape).
    eligible = {
        shape_n: width
        for shape_n, width in best.items()
        if shape_n / 4 <= n <= shape_n * 4
    }
    if not eligible:
        return default
    nearest = min(eligible, key=lambda shape_n: max(n / shape_n, shape_n / n))
    return eligible[nearest]


def _mark(msg: str) -> None:
    """Timestamped progress line on stderr: the parent watchdog treats each
    mark as proof of liveness, and a driver-side timeout log shows exactly
    how far the run got."""
    print(f"bench[{time.monotonic() - _START:7.1f}s] {msg}", file=sys.stderr, flush=True)


class _heartbeat:
    """Context manager emitting periodic ``_mark`` liveness lines from a
    daemon thread while a long silent stage (state build, XLA compile)
    runs. The parent watchdog's first-mark/idle budgets judge the child by
    its marks; the ~69 s warm-up-and-compile phase used to sit mark-silent
    long enough to trip them on a slow day — now every stage heartbeats."""

    def __init__(self, stage: str, period_s: float = 20.0) -> None:
        self._stage = stage
        self._period_s = period_s

    def __enter__(self):
        import threading

        self._stop = threading.Event()

        def beat() -> None:
            started = time.monotonic()
            while not self._stop.wait(self._period_s):
                _mark(f"{self._stage}: still running ({time.monotonic() - started:.0f}s)")

        self._thread = threading.Thread(target=beat, daemon=True)
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        self._thread.join(timeout=1.0)


def _enable_persistent_compile_cache() -> None:
    """Point JAX's persistent compilation cache at a stable directory so
    repeated bench rounds (and the watchdog's retry attempts) skip the
    multi-minute XLA compiles entirely — the cache key includes the
    computation and platform, so reuse is safe across runs of the same
    code. Best-effort: an old jax without the knobs just compiles."""
    cache_dir = os.environ.get("RAPID_TPU_JAX_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "rapid_tpu_jax"
    )
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache even quick compiles: the bench's many medium executables
        # add up, and the directory is bounded by workload variety.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _mark(f"persistent compilation cache at {cache_dir}")
    except Exception as exc:  # noqa: BLE001 — cache is
        # strictly an optimization; any flag/filesystem gap means "compile
        # as before", never "fail the bench".
        _mark(f"persistent compilation cache unavailable ({exc!r}); compiling cold")


# ---------------------------------------------------------------------------
# Derived bench metrics: pure functions, unit-audited and pinned by
# tests/test_bench_snapshot.py with plausibility bounds.
# ---------------------------------------------------------------------------


def derived_metrics(*, n: int, n_join: int, n_crash: int, k_rings: int,
                    cohorts: int, value_ms: float) -> dict:
    """Derived throughput metrics of one churn resolution.

    Units audit (the r03-r05 trajectory carried
    ``alert_deliveries_per_sec ≈ 4.96e10``, a physically implausible rate):
    the old formula multiplied every fired alert by all N members as if each
    were an independent receiver, but the engine's delivery grain is the
    COHORT — ``_deliver_alerts`` materializes one delivered-bit per
    (cohort, edge), and the ~N/C members of a cohort share that delivery.
    The honest rates are therefore:

    - ``alerts_per_sec``: fired (subject, ring) edge alerts per second —
      (joins + crashes) × K rings over the resolution wall-clock;
    - ``alert_deliveries_per_sec``: per-cohort deliveries of those alerts
      per second — alerts × C receiver cohorts over the same wall-clock
      (the BASELINE's alerts/sec axis at the engine's actual grain).
    """
    if value_ms <= 0:
        raise ValueError(f"resolution wall-clock must be positive: {value_ms}")
    alerts_fired = (n_crash + n_join) * k_rings
    seconds = value_ms / 1000.0
    return {
        "alerts_fired": alerts_fired,
        "alerts_per_sec": round(alerts_fired / seconds, 0),
        "alert_deliveries_per_sec": round(alerts_fired * cohorts / seconds, 0),
    }


#: The deployment-sizing ladder the ROADMAP's 100M question is answered
#: over: measured-validated bytes/member projected to each scale (the
#: policy re-derives per N — index lanes re-widen to int32 past 32k slots,
#: so the 10M/100M rows are honest, not a small-N extrapolation).
MEM_SIZING_SCALES = (("100k", 100_000), ("1M", 1_000_000),
                     ("10M", 10_000_000), ("100M", 100_000_000))


def memory_report(hlo_audit: dict, *, n: int, k_rings: int, cohorts: int,
                  fd_window: int = 0, use_pallas: bool = False) -> dict:
    """The bench's memory-footprint fields (ISSUE 13): bytes/member under
    the wide / compact / compact+bit-packed layouts at THIS run's geometry,
    the run's total state bytes, a 100k->100M sizing table, and a
    never-silently-absent ``mem_status``.

    ``mem_status`` is ``live:hlo-audit`` when the compiled-program audit
    measured argument bytes for both the wide and compact step entrypoints
    (memory_analysis() — the formula is then cross-checked against the
    artifact by tests/test_hlo_gate.py), else ``computed:<why>`` — the
    formula alone (exact over LANE_SPECS, which the state constructors are
    pinned against)."""
    from rapid_tpu.models.state import EngineConfig, state_bytes_per_member

    def cfg_at(n_at: int, compact: int) -> "EngineConfig":
        return EngineConfig(
            n=n_at, k=k_rings, h=9, l=4, c=min(cohorts, n_at),
            fd_window=fd_window, use_pallas=use_pallas, compact=compact,
        )

    wide_bpm = state_bytes_per_member(cfg_at(n, 0))
    compact_bpm = state_bytes_per_member(cfg_at(n, 1))
    packed_bpm = state_bytes_per_member(cfg_at(n, 1), packed=True)
    if isinstance(hlo_audit, dict) and not ("error" in hlo_audit):
        have = {
            name: entry.get("argument_bytes")
            for name, entry in hlo_audit.items()
            if isinstance(entry, dict)
        }
        if have.get("step") and have.get("step_compact"):
            mem_status = "live:hlo-audit"
        else:
            mem_status = "computed:audit-lacks-step-memory"
    else:
        reason = (
            hlo_audit.get("error", "absent") if isinstance(hlo_audit, dict)
            else "absent"
        )
        mem_status = f"computed:{reason.splitlines()[0][:80]}"
    sizing = {}
    for label, n_at in MEM_SIZING_SCALES:
        w = state_bytes_per_member(cfg_at(n_at, 0))
        c = state_bytes_per_member(cfg_at(n_at, 1))
        p = state_bytes_per_member(cfg_at(n_at, 1), packed=True)
        sizing[label] = {
            "n": n_at,
            "wide_gb": round(w * n_at / 1e9, 3),
            "compact_gb": round(c * n_at / 1e9, 3),
            "packed_gb": round(p * n_at / 1e9, 3),
            "bytes_per_member": round(c, 2),
        }
    return {
        "bytes_per_member": round(compact_bpm, 2),
        "bytes_per_member_wide": round(wide_bpm, 2),
        "bytes_per_member_packed": round(packed_bpm, 2),
        "state_bytes_total": int(compact_bpm * n),
        "mem_status": mem_status,
        "mem_sizing": sizing,
    }


def hlo_audit_summary() -> dict:
    """Per-entrypoint compiled-program facts at the fixed audit shapes
    (tools/analysis/device_program.py, session-cached): collective counts
    split hot-loop vs total, payload bytes, temp memory, and donation
    outcomes — the communication-budget companion to the latency metrics,
    diffable across BENCH_r* rounds by tools/perfview.py. Any failure
    (too few devices, an import gap) degrades to ``{"error": ...}`` —
    the audit must never take down the bench that embeds it."""
    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    if tools_dir not in sys.path:
        sys.path.append(tools_dir)
    try:
        from analysis import device_program

        # Observational mode: on a single-chip backend (the TPU v5 lite0,
        # or un-forced CPU) the four single-device entrypoints still audit;
        # the sharded pair joins whenever >= 8 devices exist. The strict
        # full-registry requirement belongs to the lockfile GATE, not here.
        facts = device_program.collect_facts(require_mesh=False)
    except Exception as exc:  # noqa: BLE001 — strictly observational: any
        # compile/import failure reports the reason in-line instead of
        # wedging the run.
        return {"error": str(exc)}
    summary = {}
    for name, entry in sorted(facts.items()):
        colls = entry["collectives"]
        # "hot-loop/" precisely: "hot-loop-cond/*" ops are GATED (they run
        # on view changes, not every round), and lumping them in would hide
        # exactly the cond->unconditional migration the gate exists to
        # catch from perfview's drift diff.
        hot = {k: v for k, v in colls.items() if k.startswith("hot-loop/")}
        summary[name] = {
            "collectives": sum(v["count"] for v in colls.values()),
            "collective_bytes": sum(v["bytes"] for v in colls.values()),
            "hot_loop_collectives": sum(v["count"] for v in hot.values()),
            "hot_loop_bytes": sum(v["bytes"] for v in hot.values()),
            "temp_bytes": entry["memory"].get("temp_bytes"),
            # Per-device argument bytes (memory_analysis): the measured
            # side of the bytes/member story — step vs step_compact is the
            # compaction saving at the audit shape.
            "argument_bytes": entry["memory"].get("argument_bytes"),
            "donation_dropped": entry["donation"]["dropped"],
        }
    return summary


def cost_report() -> dict:
    """Scaling-law cost axis of the trajectory (ISSUE 18), never silently
    absent: the zero-churn round's ``quiescent_round_cost`` (rides the
    session's ``collect_facts`` compiles the hlo_audit stage already paid;
    ROADMAP item 3's sparse restructure must shrink it round over round)
    and the fitted per-entrypoint scaling classes from the geometry
    ladder. The ladder costs real compile seconds, so
    ``RAPID_TPU_BENCH_COST_LADDER=0`` suppresses it EXPLICITLY for smoke
    runs — every suppressed or unavailable branch yields a named status,
    exactly like the headline/fleet plans."""
    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    if tools_dir not in sys.path:
        sys.path.append(tools_dir)
    try:
        from analysis import cost_model
    except Exception as exc:  # noqa: BLE001 — strictly observational
        reason = {"status": f"unavailable: {exc}"}
        return {"quiescent_round_cost": reason, "cost_fit": dict(reason)}
    try:
        quiescent = cost_model.collect_quiescent_cost(require_mesh=False)
    except Exception as exc:  # noqa: BLE001 — strictly observational
        quiescent = None
        quiescent_status = f"unavailable: {exc}"
    else:
        quiescent_status = (
            "unavailable: no sharded step in this collection "
            "(needs the 8-device mesh)"
        )
    out = {
        "quiescent_round_cost": (
            quiescent if quiescent is not None
            else {"status": quiescent_status}
        ),
    }
    if not _env_int("RAPID_TPU_BENCH_COST_LADDER", 1):
        out["cost_fit"] = {
            "status": "suppressed:RAPID_TPU_BENCH_COST_LADDER=0"
        }
        return out
    try:
        table = cost_model.collect_ladder(require_mesh=False)
        fits, refusals = cost_model.fit_ladder(table)
    except Exception as exc:  # noqa: BLE001 — strictly observational
        out["cost_fit"] = {"status": f"unavailable: {exc}"}
        return out
    out["cost_fit"] = {
        name: {fact: fit["class"] for fact, fit in sorted(per.items())}
        for name, per in sorted(fits.items())
    }
    if refusals:
        out["cost_fit_refused"] = [
            f"{name}/{fact}: {why}" for name, fact, why in refusals
        ]
    return out


def dataflow_summary() -> dict:
    """Jaxpr provenance axis of the trajectory (ISSUE 19), never silently
    absent: the observer-silence / tenant-isolation verdicts and the
    sparse-opportunity coverage from the registry trace (compile-free;
    the byte-pricing join rides the session's ``collect_facts`` compiles
    the hlo_audit stage already paid). The trace still costs a few
    seconds, so ``RAPID_TPU_BENCH_DATAFLOW=0`` suppresses it EXPLICITLY
    for smoke runs — every suppressed or unavailable branch yields a
    named status, exactly like the cost ladder."""
    if not _env_int("RAPID_TPU_BENCH_DATAFLOW", 1):
        return {
            "dataflow": {"status": "suppressed:RAPID_TPU_BENCH_DATAFLOW=0"}
        }
    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    if tools_dir not in sys.path:
        sys.path.append(tools_dir)
    try:
        from analysis import dataflow

        payload, findings = dataflow.collect_dataflow(require_mesh=False)
    except Exception as exc:  # noqa: BLE001 — strictly observational
        return {"dataflow": {"status": f"unavailable: {exc}"}}
    opp = payload["opportunity_map"]
    tenant = payload["tenant_isolation"]
    return {
        "dataflow": {
            "status": "ok" if not findings else f"findings:{len(findings)}",
            "observer_silent": all(
                e["observer_silent"] for e in payload["entrypoints"].values()
            ),
            "tenant_isolated": (
                all(t["proven"] for t in tenant.values()) if tenant else None
            ),
            "opportunity_coverage_pct": opp.get("coverage_pct"),
            "opportunity_claimed_bytes": opp.get("claimed_bytes"),
            "opportunity_total_bytes": opp.get(
                "total_collective_payload_bytes"
            ),
            **({"opportunity_status": opp["status"]} if "status" in opp else {}),
            "carry_only_lanes": payload["carry_only_lanes"],
            **({"findings": [str(f) for f in findings]} if findings else {}),
        }
    }


# ---------------------------------------------------------------------------
# The workload (runs inside the watchdogged child, or inline on CPU).
# ---------------------------------------------------------------------------

#: Per-stage watchdog budgets (seconds), stamped into each stage_begin so
#: the parent enforces them from the ledger alone. A single env override
#: (RAPID_TPU_BENCH_STAGE_TIMEOUT_S) replaces every budget for smoke runs.
STAGE_TIMEOUTS_S = {
    "devices_init": 300,
    "native_build": 300,
    "ramp": 600,
    "state_build": 900,
    "warmup_compile": 1500,
    "timed_samples": 900,
    "rtt_probe": 120,
    "xl_point": 1500,
    "stretch_point": 3000,
    "loss_variant": 900,
    "tenant_fleet": 900,
    "stream": 900,
    "chaos": 900,
    "recovery": 600,
    "hlo_audit": 600,
    "profile": 600,
}


def _stage_timeout(name: str) -> int:
    override = _env_int("RAPID_TPU_BENCH_STAGE_TIMEOUT_S", 0)
    return override if override > 0 else STAGE_TIMEOUTS_S[name]


def headline_plan(platform: str, elapsed_s: float) -> "tuple[int, str]":
    """The 1M-headline decision, pure over (platform, elapsed seconds) +
    env: returns (N to run, n1M_status). N == 0 means the point is skipped
    — but the status STILL lands in the emitted JSON, so the headline is
    never silently absent. On the accelerator (or RAPID_TPU_BENCH_XL=1)
    the point runs at the true 1M; a CPU run exercises the full stage path
    at a ramped-down N (RAPID_TPU_BENCH_XL_N, default 4096); past the XL
    time budget it is skipped-budget (a slow tunnel day must not starve
    the 100K number); RAPID_TPU_BENCH_NO_XL=1 suppresses it everywhere.
    Unit-pinned in tests/test_bench_ledger.py."""
    n_headline = 1_000_000
    if _env_flag("RAPID_TPU_BENCH_NO_XL"):
        return 0, "suppressed"
    forced = _env_flag("RAPID_TPU_BENCH_XL")
    budget_s = _env_int("RAPID_TPU_BENCH_XL_BUDGET_S", 1500)
    if elapsed_s > budget_s and not forced:
        return 0, "skipped-budget"
    if platform == "tpu" or forced:
        return n_headline, "live"
    n_ramped = _env_int("RAPID_TPU_BENCH_XL_N", 4096)
    return n_ramped, f"ramped:{n_ramped}"


def fleet_plan(platform: str, elapsed_s: float) -> "tuple[int, int, str]":
    """The multi-tenant fleet decision, pure over (platform, elapsed
    seconds) + env: returns (tenant count B, members per tenant N,
    tenant_fleet_status). B == 0 means the stage is skipped — but the
    status STILL lands in the emitted JSON, so the fleet metric is never
    silently absent (the n1M_status discipline, ISSUE 10). On the
    accelerator (or RAPID_TPU_BENCH_FLEET=1) the fleet runs at 256 tenants
    x 1024 members; a CPU run exercises the full stage path ramped down
    (RAPID_TPU_BENCH_FLEET_B/_N, default 8 x 64); past the budget
    (RAPID_TPU_BENCH_FLEET_BUDGET_S, defaulting to the XL budget) it is
    skipped-budget; RAPID_TPU_BENCH_NO_FLEET=1 suppresses it everywhere.
    Unit-pinned in tests/test_bench_ledger.py."""
    if _env_flag("RAPID_TPU_BENCH_NO_FLEET"):
        return 0, 0, "suppressed"
    forced = _env_flag("RAPID_TPU_BENCH_FLEET")
    budget_s = _env_int(
        "RAPID_TPU_BENCH_FLEET_BUDGET_S",
        _env_int("RAPID_TPU_BENCH_XL_BUDGET_S", 1500),
    )
    if elapsed_s > budget_s and not forced:
        return 0, 0, "skipped-budget"
    if platform == "tpu" or forced:
        return (
            _env_int("RAPID_TPU_BENCH_FLEET_B", 256),
            _env_int("RAPID_TPU_BENCH_FLEET_N", 1024),
            "live",
        )
    b = _env_int("RAPID_TPU_BENCH_FLEET_B", 8)
    n_t = _env_int("RAPID_TPU_BENCH_FLEET_N", 64)
    return b, n_t, f"ramped:{b}x{n_t}"


def stream_plan(platform: str, elapsed_s: float) -> "tuple[int, int, str]":
    """The streaming-serving decision, pure over (platform, elapsed
    seconds) + env: returns (waves to drive, members per cluster N,
    stream_status). waves == 0 means the stage is skipped — but the status
    STILL lands in the emitted JSON, so the sustained-throughput metrics
    are never silently absent (the n1M_status discipline). On the
    accelerator (or RAPID_TPU_BENCH_STREAM=1) the stage drives 64 waves at
    N=4096; a CPU run exercises the full pipeline ramped down
    (RAPID_TPU_BENCH_STREAM_WAVES/_N, default 12 x 96); past the budget
    (RAPID_TPU_BENCH_STREAM_BUDGET_S, defaulting to the XL budget) it is
    skipped-budget; RAPID_TPU_BENCH_NO_STREAM=1 suppresses it everywhere.
    Unit-pinned in tests/test_bench_ledger.py."""
    if _env_flag("RAPID_TPU_BENCH_NO_STREAM"):
        return 0, 0, "suppressed"
    forced = _env_flag("RAPID_TPU_BENCH_STREAM")
    budget_s = _env_int(
        "RAPID_TPU_BENCH_STREAM_BUDGET_S",
        _env_int("RAPID_TPU_BENCH_XL_BUDGET_S", 1500),
    )
    if elapsed_s > budget_s and not forced:
        return 0, 0, "skipped-budget"
    if platform == "tpu" or forced:
        return (
            _env_int("RAPID_TPU_BENCH_STREAM_WAVES", 64),
            _env_int("RAPID_TPU_BENCH_STREAM_N", 4096),
            "live",
        )
    waves = _env_int("RAPID_TPU_BENCH_STREAM_WAVES", 12)
    n_s = _env_int("RAPID_TPU_BENCH_STREAM_N", 96)
    return waves, n_s, f"ramped:{waves}x{n_s}"


def chaos_plan(platform: str, elapsed_s: float) -> "tuple[int, str]":
    """The adversarial-chaos decision, pure over (platform, elapsed
    seconds) + env: returns (fleet tenant count B, chaos_status). B == 0
    means the stage is skipped — but the status STILL lands in the emitted
    JSON, so the chaos throughput metric is never silently absent (the
    n1M_status discipline). On the accelerator (or RAPID_TPU_BENCH_CHAOS=1)
    the stage resolves 256 mixed hostile scenarios per fleet; a CPU run
    exercises the full stage path ramped down (RAPID_TPU_BENCH_CHAOS_B,
    default 12 — at least one tenant per fleet family); past the budget
    (RAPID_TPU_BENCH_CHAOS_BUDGET_S, defaulting to the XL budget) it is
    skipped-budget; RAPID_TPU_BENCH_NO_CHAOS=1 suppresses it everywhere.
    Unit-pinned in tests/test_bench_ledger.py."""
    if _env_flag("RAPID_TPU_BENCH_NO_CHAOS"):
        return 0, "suppressed"
    forced = _env_flag("RAPID_TPU_BENCH_CHAOS")
    budget_s = _env_int(
        "RAPID_TPU_BENCH_CHAOS_BUDGET_S",
        _env_int("RAPID_TPU_BENCH_XL_BUDGET_S", 1500),
    )
    if elapsed_s > budget_s and not forced:
        return 0, "skipped-budget"
    if platform == "tpu" or forced:
        return _env_int("RAPID_TPU_BENCH_CHAOS_B", 256), "live"
    from rapid_tpu.sim.fuzz import N_SLOTS

    # The ramped marker's shape is BxN: B tenants at the fuzz families'
    # shared per-tenant slot geometry (derived, so a geometry retune can't
    # leave the published status lying about what ran).
    b = _env_int("RAPID_TPU_BENCH_CHAOS_B", 12)
    return b, f"ramped:{b}x{N_SLOTS}"


def recovery_plan(platform: str, elapsed_s: float) -> "tuple[int, int, str]":
    """The self-healing drill decision (ISSUE 15), pure over (platform,
    elapsed seconds) + env: returns (members per cluster N, waves to
    stream, recovery_status). N == 0 means the stage is skipped — but the
    status STILL lands in the emitted JSON, so the MTTR metric is never
    silently absent (the n1M_status discipline). The drill: a supervised
    stream with an injected transient failure and a simulated process kill
    mid-schedule, checkpoint-cadence writes, a deterministic resume (the
    measured MTTR), and a bit-identity check against the uninterrupted
    twin. On the accelerator (or RAPID_TPU_BENCH_RECOVERY=1) it runs at
    N=4096 x 16 waves; a CPU run exercises the full drill ramped down
    (RAPID_TPU_BENCH_RECOVERY_N/_WAVES, default 64 x 6); past the budget
    (RAPID_TPU_BENCH_RECOVERY_BUDGET_S, defaulting to the XL budget) it is
    skipped-budget; RAPID_TPU_BENCH_NO_RECOVERY=1 suppresses it
    everywhere. Unit-pinned in tests/test_bench_ledger.py."""
    if _env_flag("RAPID_TPU_BENCH_NO_RECOVERY"):
        return 0, 0, "suppressed"
    forced = _env_flag("RAPID_TPU_BENCH_RECOVERY")
    budget_s = _env_int(
        "RAPID_TPU_BENCH_RECOVERY_BUDGET_S",
        _env_int("RAPID_TPU_BENCH_XL_BUDGET_S", 1500),
    )
    if elapsed_s > budget_s and not forced:
        return 0, 0, "skipped-budget"
    if platform == "tpu" or forced:
        return (
            _env_int("RAPID_TPU_BENCH_RECOVERY_N", 4096),
            _env_int("RAPID_TPU_BENCH_RECOVERY_WAVES", 16),
            "live",
        )
    n_r = _env_int("RAPID_TPU_BENCH_RECOVERY_N", 64)
    waves = _env_int("RAPID_TPU_BENCH_RECOVERY_WAVES", 6)
    return n_r, waves, f"ramped:{waves}x{n_r}"


def activity_status(stream_fields: dict, stream_status: str) -> str:
    """Device telemetry plane (ISSUE 16): the never-silently-absent status
    for the lane-derived activity numbers — "measured" when the stream
    stage actually fetched a numeric active fraction, otherwise the stage's
    own skip reason (ramped:WxN / skipped-budget / suppressed), so
    perfview's activity-missing flag only ever fires on instrumentation
    LOSS (an audited round that dropped both value and status)."""
    if isinstance(stream_fields.get("stream_active_fraction"), (int, float)):
        return "measured"
    return stream_status


def trace_status(stream_fields: dict, stream_status: str) -> str:
    """Round-trace ring (ISSUE 17): the never-silently-absent status for
    the ring-derived trajectory digest — "measured" when the stream stage
    drained a numeric rounds-to-decision p99 out of the decoded rings,
    otherwise the stage's own skip reason (ramped:WxN / skipped-budget /
    suppressed), so perfview's trace-missing flag only ever fires on
    instrumentation LOSS (an audited round that dropped both the digest
    and the status)."""
    trajectory = stream_fields.get("round_trajectory") or {}
    if isinstance(
        trajectory.get("rounds_to_decision_p99"), (int, float)
    ):
        return "measured"
    return stream_status


def _parse_scale(spec: str) -> int:
    """'10M' -> 10_000_000, '250k' -> 250_000, bare ints pass through; 0 on
    anything unparseable (the stretch point is opt-in — a typo'd env value
    must skip it loudly, never crash the whole bench)."""
    s = spec.strip().lower()
    mult = 1
    if s.endswith("m"):
        mult, s = 1_000_000, s[:-1]
    elif s.endswith("k"):
        mult, s = 1_000, s[:-1]
    try:
        return int(s) * mult
    except ValueError:
        return 0


def run_workload(ledger, profile_dir=None) -> None:
    if _env_flag("RAPID_TPU_BENCH_SIMULATE_WEDGE") and _env_flag("RAPID_TPU_BENCH_CHILD"):
        # Test hook for the watchdog/loud-failure path: the ACCELERATOR
        # CHILD behaves exactly like a wedged axon client — alive but
        # silent, forever — while a CPU fallback/continuation still runs
        # (that is what the real wedge looks like). Before any jax import
        # so the simulation cannot touch a real backend.
        while True:
            time.sleep(60)
    from rapid_tpu.utils.ledger import LedgerEvent

    with ledger.stage("devices_init", timeout_s=_stage_timeout("devices_init")):
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            # sitecustomize imported jax before us; env alone is too late —
            # and the axon plugin initializes its backend even under
            # JAX_PLATFORMS=cpu unless the live config is overridden.
            from rapid_tpu.utils.platform import force_platform

            force_platform("cpu")
        import jax

        platform = jax.devices()[0].platform
        _mark(f"devices initialized: platform={platform} count={len(jax.devices())}")
        if platform == "cpu":
            # DELIBERATELY no persistent compile cache on the CPU backend:
            # executables deserialized from it corrupt the heap under
            # donated executions on this jaxlib — sometimes a glibc abort,
            # sometimes SILENT scribbling over unrelated live buffers.
            # Root-caused twice: first for sharded executables (the
            # device_program audit scopes the cache off,
            # tools/analysis/device_program.py), then for single-device
            # ones by the recovery drill's bit-identity assertion — the
            # one bench workload that CHECKS bits caught what every other
            # stage silently tolerated. CPU runs are ramped-down smoke
            # paths; cold compiles cost seconds and measure real code.
            _mark("persistent compilation cache disabled on cpu "
                  "(deserialized executables corrupt donated executions; "
                  "see tools/analysis/device_program.py)")
        else:
            _enable_persistent_compile_cache()

    import numpy as np

    from rapid_tpu.utils import engine_telemetry

    with ledger.stage("native_build", timeout_s=_stage_timeout("native_build")):
        from rapid_tpu.utils._native import ensure_built

        ensure_built()  # compile the native host library outside any event loop
        _mark("native library built")

    from rapid_tpu.models.virtual_cluster import VirtualCluster

    # N is env-overridable for smoke-testing the bench machinery itself
    # (watchdog, fallback, JSON shape) at small scale; the real scenario is
    # the 100K default.
    n = _env_int("RAPID_TPU_BENCH_N", 100_000)
    churn_frac = 0.05  # BASELINE config 4: 5% churn (half joins, half crashes)
    n_join = int(n * churn_frac / 2)
    n_crash = int(n * churn_frac / 2)
    fd_threshold = 3
    k_rings = 10
    cohorts = 64
    delivery_spread = 2
    baseline_target_ms = 500.0
    max_view_changes = 4  # churn resolves in >=2 cuts; allow stragglers

    # The Mosaic kernel path is strictly an optimization: smoke-test it once
    # (pallas_usable) and drop to the bit-identical jnp core if it fails,
    # rather than dying mid-benchmark on the accelerator.
    from rapid_tpu.ops.pallas_kernels import pallas_usable

    use_pallas = pallas_usable()
    _mark(f"pallas kernel usable: {use_pallas}")
    # Resolved once: env override or newest committed autotune evidence.
    lanes_main = _autotuned_lanes(n, "RAPID_TPU_BENCH_LANES")
    lanes_xl = _autotuned_lanes(1_000_000, "RAPID_TPU_BENCH_LANES_1M")
    if platform == "tpu" and not use_pallas:
        print("bench: pallas kernel unusable; using jnp core", file=sys.stderr)

    # Staged N ramp: tiny engine convergences BEFORE committing to the
    # multi-minute full-N state build + compile, each its own budgeted
    # ledger stage — a wedged backend dies at a cheap, named stage instead
    # of silently inside the 69 s warm-up. Default: one 4K step on the
    # accelerator, none on CPU (the CPU fallback pays compile time twice
    # for no diagnostic value there).
    ramp_spec = os.environ.get(
        "RAPID_TPU_BENCH_RAMP", "4096" if platform == "tpu" else ""
    )
    for ramp_field in ramp_spec.split(","):
        if not ramp_field.strip():
            continue
        ramp_n = int(ramp_field)
        with ledger.stage("ramp", timeout_s=_stage_timeout("ramp"), n=ramp_n), \
                _heartbeat(f"ramp N={ramp_n}"):
            vcr = VirtualCluster.create(
                ramp_n, k=k_rings, h=9, l=4, cohorts=min(cohorts, ramp_n),
                fd_threshold=fd_threshold, seed=0, use_pallas=use_pallas,
                delivery_spread=delivery_spread, pallas_lanes=128,
            )
            vcr.assign_cohorts_roundrobin()
            vcr.crash(
                np.random.default_rng(0).choice(
                    ramp_n, size=max(1, ramp_n // 100), replace=False
                )
            )
            vcr.sync()
            _, ramp_decided, _, _ = vcr.run_to_decision(max_steps=96)
            _mark(f"ramp N={ramp_n}: decided={ramp_decided}")
            del vcr

    def build(seed: int, spread: int = delivery_spread, prob_permille: int = 1000):
        vc = VirtualCluster.create(
            n,
            n_slots=n + n_join,
            k=k_rings,
            h=9,
            l=4,
            cohorts=cohorts,
            fd_threshold=fd_threshold,
            seed=seed,
            use_pallas=use_pallas,
            delivery_spread=spread,
            concurrent_coordinators=2,
            delivery_prob_permille=prob_permille,
            pallas_lanes=lanes_main,
        )
        vc.assign_cohorts_roundrobin()
        rng = np.random.default_rng(seed + 1000)
        vc.stagger_fd_counts(rng, spread_rounds=3)
        victims = rng.choice(n, size=n_crash, replace=False)
        joiners = np.arange(n, n + n_join)
        vc.crash(victims)
        vc.inject_join_wave(joiners)
        return vc, victims

    def resolve_churn(vc) -> int:
        """Resolve the whole churn in ONE device dispatch: the multi-cut
        loop applies every view change on device and the observation comes
        back in one small fetch — zero per-cut round trips (each would be a
        full tunnel RTT)."""
        # min_cuts=1: joins == crashes, so the TARGET equals the starting
        # membership — at least one committed cut distinguishes "resolved"
        # from "never started".
        rounds, cuts, resolved, sizes = vc.run_until_membership(
            n, max_steps=96 * max_view_changes, max_cuts=max_view_changes,
            min_cuts=1,
        )
        assert resolved, (
            f"churn unresolved after {cuts} view changes in {rounds} rounds "
            f"(sizes {sizes})"
        )
        return cuts

    # Warm-up: compile every branch the timed run takes (convergence loop,
    # view-change application, second-cut re-entry). Heartbeat throughout:
    # state build + compile is the longest mark-silent stretch of the run
    # (~69 s cold), and the parent watchdog judges liveness by marks.
    with ledger.stage("state_build", timeout_s=_stage_timeout("state_build"), n=n):
        with _heartbeat(f"N={n} state build"):
            vc, _ = build(seed=0)
            vc.sync()
    _mark(f"N={n} state built and on device; compiling engine (warm-up run)")
    with ledger.stage("warmup_compile", timeout_s=_stage_timeout("warmup_compile"), n=n):
        with engine_telemetry.CompileDelta() as warmup_compiles:
            with _heartbeat(f"N={n} warm-up compile"):
                resolve_churn(vc)
    ledger.emit(LedgerEvent.COMPILE_STATS, stage="warmup_compile",
                **warmup_compiles.delta)
    ledger.emit(LedgerEvent.DEVICE_MEMORY, stage="warmup_compile",
                **engine_telemetry.device_memory_snapshot())
    _mark("warm-up convergence done (executables cached)")

    # Timed runs on fresh state (same shapes -> cached executables).
    samples = []
    cuts_per_sample = []
    with ledger.stage("timed_samples", timeout_s=_stage_timeout("timed_samples"), n=n):
        for rep in range(3):
            vc, victims = build(seed=rep)
            # Real barrier: state upload/init must complete before the clock
            # starts (block_until_ready is advisory on tunnel backends).
            vc.sync()
            start = time.perf_counter()
            cuts = resolve_churn(vc)
            elapsed_ms = (time.perf_counter() - start) * 1000.0
            # resolve_churn's membership_size reads are scalar fetches — the
            # clock stops after real device completion.
            assert vc.membership_size == n
            assert not vc.alive_mask[victims].any()
            assert vc.alive_mask[n : n + n_join].all()
            samples.append(elapsed_ms)
            cuts_per_sample.append(cuts)
            _mark(f"sample {rep + 1}/3: {elapsed_ms:.1f} ms ({cuts} view changes)")

    # Fixed device<->host round-trip latency of this environment (the axon
    # tunnel); a co-located deployment would not pay it.
    import jax.numpy as jnp

    with ledger.stage("rtt_probe", timeout_s=_stage_timeout("rtt_probe")):
        probe = jax.jit(lambda a: a + 1)
        int(probe(jnp.int32(1)))
        t0 = time.perf_counter()
        int(probe(jnp.int32(2)))
        rtt_ms = (time.perf_counter() - t0) * 1000.0

    # The crash-1% scale-point family: the 1M-member HEADLINE metric
    # (n1M_crash1pct_ms — ROADMAP item 1 promoted it from side metric to
    # the first-class scale number) and the opt-in 10M stretch point. One
    # measurement recipe per point: fresh state, warm-up compile, fresh
    # state again, one timed single-dispatch convergence — its own ledger
    # stage, its own watchdog budget, per-device memory from the
    # engine-telemetry tier recorded alongside.
    def crash1pct_point(stage: str, n_point: int, lanes_point: int):
        # The bracketing ledger stage is opened by the CALLER with a literal
        # name (the ledger lint's vocabulary rule); ``stage`` here only
        # labels marks and the returned telemetry.
        cohorts_point = min(8, n_point)
        n_crash_point = max(1, n_point // 100)

        def build_point(seed: int):
            vcx = VirtualCluster.create(
                n_point,
                k=10,
                h=9,
                l=4,
                cohorts=cohorts_point,
                fd_threshold=fd_threshold,
                seed=seed,
                use_pallas=use_pallas,
                delivery_spread=delivery_spread,
                pallas_lanes=lanes_point,
            )
            vcx.assign_cohorts_roundrobin()
            vcx.crash(
                np.random.default_rng(seed).choice(
                    n_point, size=n_crash_point, replace=False
                )
            )
            return vcx

        with _heartbeat(f"{stage} N={n_point} state build"):
            vcx = build_point(7)
            vcx.sync()
        _mark(f"{stage}: N={n_point} state on device; compiling (warm-up)")
        with engine_telemetry.CompileDelta() as point_compiles:
            with _heartbeat(f"{stage} warm-up compile"):
                vcx.run_to_decision(max_steps=96)  # warm-up/compile
        vcx = build_point(8)
        vcx.sync()
        t0 = time.perf_counter()
        _, decided_pt, _, _ = vcx.run_to_decision(max_steps=96)
        point_ms = (time.perf_counter() - t0) * 1000.0
        assert decided_pt and vcx.membership_size == n_point - n_crash_point
        _mark(f"{stage}: N={n_point} crash1pct {point_ms:.1f} ms")
        return point_ms, point_compiles.delta, engine_telemetry.device_memory_snapshot()

    # Headline policy (headline_plan, pure + unit-pinned) — the point is
    # NEVER silently absent: the emitted JSON always carries either the
    # measured 1M number or an explicit n1M_status marker.
    n_headline = 1_000_000
    xl_ms = None
    xl_memory = None
    xl_n, xl_status = headline_plan(platform, time.monotonic() - _START)
    if xl_n == 0:
        _mark(f"headline 1M point not run: {xl_status}")
    else:
        with ledger.stage("xl_point", timeout_s=_stage_timeout("xl_point"), n=xl_n):
            xl_ms, xl_compiles, xl_memory = crash1pct_point(
                "xl_point", xl_n, lanes_xl if xl_n >= n_headline else 128
            )
        ledger.emit(LedgerEvent.COMPILE_STATS, stage="xl_point", **xl_compiles)
        ledger.emit(LedgerEvent.DEVICE_MEMORY, stage="xl_point", **xl_memory)

    # The 10M stretch point, strictly opt-in: RAPID_TPU_BENCH_STRETCH=10M
    # (any <int>[M|k] spec works — a small value exercises the stage on
    # CPU). Its own registered ledger stage and watchdog budget.
    stretch_ms = None
    stretch_n = None
    stretch_spec = os.environ.get("RAPID_TPU_BENCH_STRETCH", "")
    if stretch_spec:
        stretch_n = _parse_scale(stretch_spec)
        if stretch_n <= 0:
            _mark(f"unparseable RAPID_TPU_BENCH_STRETCH={stretch_spec!r}; skipping")
            stretch_n = None
        else:
            with ledger.stage(
                "stretch_point",
                timeout_s=_stage_timeout("stretch_point"),
                n=stretch_n,
            ):
                stretch_ms, stretch_compiles, stretch_memory = crash1pct_point(
                    "stretch_point",
                    stretch_n,
                    lanes_xl if stretch_n >= n_headline else 128,
                )
            ledger.emit(LedgerEvent.COMPILE_STATS, stage="stretch_point",
                        **stretch_compiles)
            ledger.emit(LedgerEvent.DEVICE_MEMORY, stage="stretch_point",
                        **stretch_memory)

    # Adverse-network variant: the SAME churn resolved under the chaos
    # subsystem's churn_under_loss fault schedule (rapid_tpu/sim) — its 5%
    # symmetric loss compiled onto the engine's delivery knobs by the shared
    # definition (sim/faults.loss_as_engine_delivery: a lost broadcast is a
    # delivery delayed into the redelivery horizon). This is the perf
    # trajectory's first adverse-network axis: resolution latency under
    # loss, not just clean-network. Skipped past the XL budget like the 1M
    # point (a slow tunnel day must not starve the headline number).
    from rapid_tpu.sim.faults import loss_as_engine_delivery
    from rapid_tpu.sim.fuzz import churn_under_loss

    loss_ms = None
    loss_permille = max(
        int(e.args["permille"])
        for e in churn_under_loss(0).events
        if e.kind == "loss"
    )
    loss_knobs = loss_as_engine_delivery(loss_permille)
    loss_budget_s = _env_int("RAPID_TPU_BENCH_XL_BUDGET_S", 1500)
    if _env_flag("RAPID_TPU_BENCH_NO_LOSS"):
        # Operator knob (sweeps, smoke runs): drop the adverse-network
        # variant without touching the shared XL budget that also gates
        # the headline point.
        _mark("skipping churn_under_loss variant: RAPID_TPU_BENCH_NO_LOSS")
    elif time.monotonic() - _START <= loss_budget_s:
        with ledger.stage("loss_variant", timeout_s=_stage_timeout("loss_variant"), n=n):
            vc, _ = build(
                seed=100,
                spread=loss_knobs["delivery_spread"],
                prob_permille=loss_knobs["delivery_prob_permille"],
            )
            vc.sync()
            _mark(f"loss variant ({loss_permille} permille): compiling (warm-up)")
            with _heartbeat("loss-variant warm-up compile"):
                resolve_churn(vc)
            loss_samples = []
            for rep in range(2):
                vc, victims = build(
                    seed=101 + rep,
                    spread=loss_knobs["delivery_spread"],
                    prob_permille=loss_knobs["delivery_prob_permille"],
                )
                vc.sync()
                t0 = time.perf_counter()
                cuts = resolve_churn(vc)
                loss_samples.append((time.perf_counter() - t0) * 1000.0)
                assert vc.membership_size == n and not vc.alive_mask[victims].any()
                _mark(
                    f"loss sample {rep + 1}/2: {loss_samples[-1]:.1f} ms ({cuts} view changes)"
                )
            loss_ms = min(loss_samples)
    else:
        _mark("skipping churn_under_loss variant: past the XL time budget")

    # Multi-tenant fleet point (ISSUE 10 / ROADMAP item 4): B independent
    # clusters — a MIXED bag of scenario families (crash wave, join wave,
    # equal-churn) with independent seeds and per-tenant H/L knobs —
    # resolved in ONE lockstep fleet-wave dispatch (rapid_tpu/tenancy).
    # The metric is tenant_view_changes_per_sec: total view changes
    # committed across the fleet over the wall clock of the single
    # dispatch. Never silently absent: tenant_fleet_status always lands in
    # the emitted JSON (the n1M_status discipline); CPU runs exercise the
    # stage ramped-down.
    fleet_b, fleet_n, fleet_status = fleet_plan(
        platform, time.monotonic() - _START
    )
    fleet_vcps = None
    fleet_cuts_total = None
    fleet_wall_ms = None
    fleet_memory = None
    fleet_activity = None
    fleet_conflict_rates = None
    if fleet_b == 0:
        _mark(f"tenant fleet stage not run: {fleet_status}")
    else:
        from rapid_tpu.tenancy import TenantFleet

        fleet_max_steps = 96  # fixed lockstep recipe: the metric divides by
        # the wall clock of exactly this many batched rounds

        def build_fleet(seed0: int):
            """B tenants cycling three scenario families, per-tenant knob
            mix, independent seeds; returns (fleet, targets, min_cuts)."""
            n_extra = max(2, fleet_n // 50)
            clusters, targets = [], []
            for i in range(fleet_b):
                h, l = ((9, 4), (8, 3))[i % 2]
                vc = VirtualCluster.create(
                    fleet_n, n_slots=fleet_n + n_extra, k=k_rings, h=h, l=l,
                    cohorts=min(8, fleet_n), fd_threshold=fd_threshold,
                    seed=seed0 + i, delivery_spread=delivery_spread,
                    telemetry=True,
                )
                vc.assign_cohorts_roundrobin()
                rng = np.random.default_rng(seed0 + 10_000 + i)
                vc.stagger_fd_counts(rng, spread_rounds=3)
                family = i % 3
                if family == 0:  # crash wave
                    vc.crash(rng.choice(fleet_n, size=n_extra, replace=False))
                    targets.append(fleet_n - n_extra)
                elif family == 1:  # join wave
                    vc.inject_join_wave(
                        np.arange(fleet_n, fleet_n + n_extra)
                    )
                    targets.append(fleet_n + n_extra)
                else:  # equal churn: joins == crashes, target == start —
                    # min_cuts=1 below is what distinguishes "resolved"
                    # from "never started" for these tenants
                    vc.crash(rng.choice(fleet_n, size=n_extra, replace=False))
                    vc.inject_join_wave(
                        np.arange(fleet_n, fleet_n + n_extra)
                    )
                    targets.append(fleet_n)
                clusters.append(vc)
            return TenantFleet.from_clusters(clusters), targets

        with ledger.stage(
            "tenant_fleet", timeout_s=_stage_timeout("tenant_fleet"),
            n=fleet_b * fleet_n,
        ):
            with _heartbeat(f"tenant_fleet B={fleet_b} N={fleet_n} warm-up"):
                with engine_telemetry.CompileDelta() as fleet_compiles:
                    fleet, targets = build_fleet(seed0=50_000)
                    fleet.sync()
                    fleet.run_until_membership(
                        targets, max_steps=fleet_max_steps, max_cuts=4,
                        min_cuts=1,
                    )
            fleet, targets = build_fleet(seed0=60_000)
            fleet.sync()
            t0 = time.perf_counter()
            _, cuts, resolved, _ = fleet.run_until_membership(
                targets, max_steps=fleet_max_steps, max_cuts=4, min_cuts=1,
            )
            fleet_wall_ms = (time.perf_counter() - t0) * 1000.0
            assert resolved.all(), (
                f"fleet tenants unresolved: {np.nonzero(~resolved)[0].tolist()}"
            )
            fleet_cuts_total = int(cuts.sum())
            fleet_vcps = fleet_cuts_total / (fleet_wall_ms / 1000.0)
            # Device telemetry plane (ISSUE 16): the per-tenant conflict
            # rates from the fleet's lanes — the sync boundary below is
            # what refreshes the host cache (timing already captured).
            fleet.sync()
            fleet_activity = fleet.activity
            fleet_conflict_rates = [
                round(a["conflict_rate"], 6) for a in fleet.tenant_activity
            ]
            fleet_memory = engine_telemetry.device_memory_snapshot()
            _mark(
                f"tenant_fleet: {fleet_b} tenants x {fleet_n} members, "
                f"{fleet_cuts_total} view changes in {fleet_wall_ms:.1f} ms "
                f"({fleet_vcps:.1f}/s)"
            )
        ledger.emit(LedgerEvent.COMPILE_STATS, stage="tenant_fleet",
                    **fleet_compiles.delta)
        ledger.emit(LedgerEvent.DEVICE_MEMORY, stage="tenant_fleet",
                    **fleet_memory)

    # Streaming serving point (ISSUE 11 / ROADMAP item 4): sustained
    # throughput under CONTINUOUS Poisson churn through the pipelined
    # dispatch path (rapid_tpu/serving) — per-wave fault deltas double-
    # buffered against in-flight dispatches, host sync only at explicit
    # fetch boundaries. Both serving paths stream: the single cluster
    # (crash+join churn) and the tenant fleet (per-tenant crash streams).
    # The emitted numbers are the ones a serving system publishes —
    # sustained view-changes/sec, p99 alert->commit latency, and the
    # overlap-efficiency ratio (1 - host-fetch-blocked/wall, computed from
    # the stream_fetch dispatch-phase histogram the dashboards also
    # render). Never silently absent: stream_status always lands in the
    # emitted JSON (the n1M_status discipline).
    stream_waves, stream_n, stream_status = stream_plan(
        platform, time.monotonic() - _START
    )
    stream_fields = {}
    stream_memory = None
    if stream_waves == 0:
        _mark(f"stream stage not run: {stream_status}")
    else:
        from rapid_tpu.serving import (
            FleetPoissonChurn, PoissonChurn, StreamDriver,
        )
        from rapid_tpu.tenancy import TenantFleet
        from rapid_tpu.utils.histogram import LogHistogram as _StreamHist

        stream_b = 4  # fleet-path tenants: enough to exercise the stacked pipe
        rounds_per_wave = _env_int("RAPID_TPU_BENCH_STREAM_ROUNDS", 8)
        # Round-trace ring capacity (ISSUE 17): sized to the whole stage by
        # default so every wave's span survives to the drain decode (the
        # trajectory quantiles cover all waves, waves_evicted == 0); a
        # smaller override exercises the eviction accounting instead.
        stream_trace_r = _env_int(
            "RAPID_TPU_BENCH_TRACE_R", stream_waves * rounds_per_wave
        )
        # Fresh-slot headroom for the join half of the churn: the generator
        # never reuses a slot (the engine's UUID discipline), so the slot
        # table must hold every joiner the whole stream can admit.
        stream_slots = stream_n + 2 * stream_waves

        def build_stream_cluster(seed: int):
            # telemetry=True: the stream stage is where the device telemetry
            # plane's activity numbers come from (ISSUE 16) — the lanes ride
            # the same donated dispatches and the digest is fetched only at
            # the drain boundary, so the measured overlap is unchanged.
            # trace=R: the ring rides the same donated dispatches and is
            # decoded from the drain-boundary digest fetch — the measured
            # overlap is unchanged (trace-on/off bit-identity is pinned in
            # tests/test_trace_ring.py).
            vcs = VirtualCluster.create(
                stream_n, n_slots=stream_slots, k=k_rings, h=9, l=4,
                cohorts=min(8, stream_n), fd_threshold=fd_threshold,
                seed=seed, delivery_spread=delivery_spread, telemetry=True,
                trace=stream_trace_r,
            )
            vcs.assign_cohorts_roundrobin()
            return vcs

        def build_stream_fleet(seed0: int):
            clusters = []
            for i in range(stream_b):
                vcs = VirtualCluster.create(
                    stream_n, k=k_rings, h=9, l=4,
                    cohorts=min(8, stream_n), fd_threshold=fd_threshold,
                    seed=seed0 + i, delivery_spread=delivery_spread,
                    telemetry=True, trace=stream_trace_r,
                )
                vcs.assign_cohorts_roundrobin()
                clusters.append(vcs)
            return TenantFleet.from_clusters(clusters)

        with ledger.stage(
            "stream", timeout_s=_stage_timeout("stream"),
            n=stream_waves * rounds_per_wave,
        ):
            with _heartbeat(f"stream warm-up N={stream_n}"):
                with engine_telemetry.CompileDelta() as stream_compiles:
                    # Warm the compiled programs the stream enqueues —
                    # engine_step at the cluster shape, fleet_step at the
                    # stacked shape, AND the churn-injection programs
                    # (crash scatter, predecessor_of_keys + the join
                    # scatters) — so the timed stream measures dispatch
                    # overlap, not XLA compiles. Per-delta-SIZE shapes
                    # (a 2-crash wave, a 3-join wave) still compile fresh
                    # mid-stream; stream_mid_stream_compiles below keeps
                    # that residual pollution observable instead of
                    # pretending it away.
                    warm = build_stream_cluster(seed=7_000)
                    warm.crash([0])
                    warm.inject_join_wave([stream_n])
                    warm.step()
                    warm.sync()
                    warm_fleet = build_stream_fleet(seed0=7_100)
                    warm_fleet.stream_crash([(0, 1)])
                    warm_fleet.step()
                    warm_fleet.sync()
                    del warm, warm_fleet
            with engine_telemetry.CompileDelta() as stream_mid:
                # Single-cluster path: seeded Poisson crash+join churn,
                # waves pipelined `depth` deep behind in-flight dispatches.
                vcs = build_stream_cluster(seed=7_200)
                vcs.sync()
                stream_driver = StreamDriver(
                    vcs, rounds_per_wave=rounds_per_wave, depth=2
                )
                for wave in PoissonChurn(
                    stream_n, stream_slots, rate=2.0, seed=7_300
                ).waves(stream_waves):
                    stream_driver.submit(wave)
                cluster_stream = stream_driver.drain()
                _mark(
                    f"stream cluster: {cluster_stream.cuts} view changes over "
                    f"{cluster_stream.waves} waves in {cluster_stream.wall_ms:.1f} ms "
                    f"(overlap {cluster_stream.overlap_efficiency})"
                )
                # Fleet path: the same pipeline over the stacked engine.
                fleet_s = build_stream_fleet(seed0=7_400)
                fleet_s.sync()
                fleet_stream_driver = StreamDriver(
                    fleet_s, rounds_per_wave=rounds_per_wave, depth=2
                )
                for wave in FleetPoissonChurn(
                    stream_b, stream_n, rate=0.5, seed=7_500
                ).waves(stream_waves):
                    fleet_stream_driver.submit(wave)
                fleet_stream = fleet_stream_driver.drain()
                _mark(
                    f"stream fleet: {fleet_stream.cuts} view changes over "
                    f"{fleet_stream.waves} waves in {fleet_stream.wall_ms:.1f} ms"
                )
            # Combined sustained metrics over BOTH paths: total committed
            # view changes over total stream wall clock, p99 over the
            # merged alert->commit histograms, overlap over the summed
            # fetch-blocked time (all three checkable from the per-target
            # telemetry scrapes).
            wall_ms_total = cluster_stream.wall_ms + fleet_stream.wall_ms
            cuts_total = cluster_stream.cuts + fleet_stream.cuts
            fetch_ms_total = (
                cluster_stream.fetch_blocked_ms + fleet_stream.fetch_blocked_ms
            )
            merged_latency = _StreamHist.merged(
                hist for target in (vcs, fleet_s)
                if (hist := target.metrics.timings.get(
                    "engine_stream_alert_to_commit"
                )) is not None
            )
            stream_fields = {
                "stream_view_changes_per_sec": (
                    round(cuts_total / (wall_ms_total / 1000.0), 2)
                    if wall_ms_total > 0 else None
                ),
                "stream_p99_alert_to_commit_ms": (
                    round(float(merged_latency.quantile(0.99)), 3)
                    if merged_latency.count else None
                ),
                "stream_overlap_efficiency": (
                    round(max(0.0, min(1.0, 1.0 - fetch_ms_total / wall_ms_total)), 4)
                    if wall_ms_total > 0 else None
                ),
                "stream_waves": stream_waves,
                "stream_rounds_per_wave": rounds_per_wave,
                "stream_n": stream_n,
                "stream_fleet_tenants": stream_b,
                "stream_view_changes": cuts_total,
                "stream_wall_ms": round(wall_ms_total, 3),
                # Always floats post-drain (0.0 on degenerate streams —
                # the ISSUE-15 rate-math contract), never None.
                "stream_cluster_view_changes_per_sec": round(
                    cluster_stream.view_changes_per_sec, 2
                ),
                "stream_fleet_view_changes_per_sec": round(
                    fleet_stream.view_changes_per_sec, 2
                ),
                "stream_h2d_bytes": cluster_stream.h2d_bytes + fleet_stream.h2d_bytes,
                # Compiles that landed INSIDE the timed stream (per-delta-
                # size scatter shapes the warm-up cannot enumerate): the
                # reader's gauge for how much of wall_ms/p99 is compile
                # pollution rather than dispatch overlap.
                "stream_mid_stream_compiles": stream_mid.delta.get("compiles", 0),
                "stream_mid_stream_compile_ms": stream_mid.delta.get(
                    "compile_ms", 0.0
                ),
            }
            # Device telemetry plane (ISSUE 16): the activity numbers from
            # BOTH serving paths' lanes, refreshed by the drains above. The
            # two paths run different slot-table geometries, so the mean
            # active fraction is rounds-weighted over per-engine fractions
            # rather than pooled over raw counters.
            activity_summaries = [
                a for a in (
                    vcs.activity, *(fleet_s.tenant_activity or ())
                ) if a is not None
            ]
            activity_rounds = sum(s["rounds"] for s in activity_summaries)
            decisions_fast = sum(
                s["decisions_fast"] for s in activity_summaries
            )
            decisions_total = decisions_fast + sum(
                s["decisions_classic"] for s in activity_summaries
            )
            if activity_rounds:
                stream_fields.update({
                    "stream_active_fraction": round(
                        sum(
                            s["active_fraction"] * s["rounds"]
                            for s in activity_summaries
                        ) / activity_rounds, 6,
                    ),
                    "stream_peak_active_fraction": round(
                        max(
                            s["peak_active_fraction"]
                            for s in activity_summaries
                        ), 6,
                    ),
                    "stream_fast_path_share": round(
                        decisions_fast / decisions_total, 4,
                    ) if decisions_total else 0.0,
                })
            # Round-trace ring digest (ISSUE 17): per-wave rounds-to-
            # decision quantiles and the active-trajectory p99, decoded
            # from BOTH serving paths' rings at their drain boundaries
            # (StreamDriver.last_trajectory — pure host arithmetic over
            # the one drain-time digest fetch). The headline numbers take
            # the WORST path (a serving p99 is the slowest story told).
            trajectories = {
                "cluster": stream_driver.last_trajectory,
                "fleet": fleet_stream_driver.last_trajectory,
            }
            drained = [t for t in trajectories.values() if t]

            def _worst(key):
                vals = [
                    t[key] for t in drained
                    if isinstance(t.get(key), (int, float))
                ]
                return max(vals) if vals else None

            stream_fields["round_trajectory"] = {
                "trace_capacity": stream_trace_r,
                "rounds_to_decision_p50": _worst("rounds_to_decision_p50"),
                "rounds_to_decision_p99": _worst("rounds_to_decision_p99"),
                "rounds_to_decision_max": _worst("rounds_to_decision_max"),
                "active_p99": _worst("active_p99"),
                "waves_evicted": sum(
                    t.get("waves_evicted") or 0 for t in drained
                ),
                **trajectories,
            }
            # Zero-churn stability soak: a quiet engine must READ zero —
            # published explicitly (0.0 is a measurement, not an absence;
            # perfview's activity-missing flag polices exactly this).
            quiet = build_stream_cluster(seed=7_600)
            for _ in range(rounds_per_wave):
                quiet.step()
            quiet.sync()
            stream_fields["quiescent_active_fraction"] = float(
                quiet.activity["active_fraction"]
            )
            del quiet
            stream_memory = engine_telemetry.device_memory_snapshot()
            _mark(
                f"stream: {cuts_total} view changes in {wall_ms_total:.1f} ms "
                f"({stream_fields['stream_view_changes_per_sec']}/s, overlap "
                f"{stream_fields['stream_overlap_efficiency']})"
            )
        ledger.emit(LedgerEvent.COMPILE_STATS, stage="stream",
                    **stream_compiles.delta)
        ledger.emit(LedgerEvent.DEVICE_MEMORY, stage="stream",
                    **stream_memory)

    # Adversarial-chaos point (ISSUE 12): B mixed hostile scenarios —
    # Byzantine false alerts against the H/L watermarks, committee crashes
    # inside the hier reconfiguration window, plus the honest families —
    # compiled per tenant and resolved in batched fleet-wave dispatches
    # with the stability soak (rapid_tpu/tenancy/chaos.py). The metric is
    # chaos_scenarios_per_sec: scenarios resolved (and oracle-checked
    # clean) per second of fleet dispatch. Never silently absent:
    # chaos_status always lands in the emitted JSON (the n1M_status
    # discipline); CPU runs exercise the stage ramped-down.
    chaos_b, chaos_status = chaos_plan(platform, time.monotonic() - _START)
    chaos_fields = {}
    if chaos_b == 0:
        _mark(f"chaos stage not run: {chaos_status}")
    else:
        from rapid_tpu.tenancy import chaos as tchaos

        with ledger.stage("chaos", timeout_s=_stage_timeout("chaos"), n=chaos_b):
            with _heartbeat(f"chaos fleet B={chaos_b} warm-up"):
                with engine_telemetry.CompileDelta() as chaos_compiles:
                    # Warm the batched wave/step executables at the exact
                    # [B, geometry] shape, so the timed round measures
                    # dispatch throughput, not XLA compiles.
                    tchaos.fuzz_fleet(
                        chaos_b, base_seed=70_000, shrink_failures=False
                    )
            chaos_summary = tchaos.fuzz_fleet(
                chaos_b, base_seed=71_000, shrink_failures=False
            )
            assert not chaos_summary["violations"], (
                "chaos fleet violations:\n"
                + "\n".join(chaos_summary["violations"])
            )
            chaos_fields = {
                "chaos_scenarios_per_sec": chaos_summary["scenarios_per_sec"],
                "chaos_tenants": chaos_b,
                "chaos_dispatches": chaos_summary["dispatches"],
                "chaos_view_changes": chaos_summary["total_cuts"],
                "chaos_wall_ms": chaos_summary["wall_ms"],
                "chaos_families": len(chaos_summary["families"]),
            }
            _mark(
                f"chaos: {chaos_b} hostile scenarios over "
                f"{len(chaos_summary['families'])} families in "
                f"{chaos_summary['wall_ms']:.1f} ms "
                f"({chaos_summary['scenarios_per_sec']:.1f} scenarios/s), "
                f"0 violations"
            )
        ledger.emit(LedgerEvent.COMPILE_STATS, stage="chaos",
                    **chaos_compiles.delta)

    # Self-healing drill (ISSUE 15): a supervised stream with an injected
    # transient dispatch failure and a simulated process kill mid-schedule;
    # the supervisor retries on seeded backoff, writes checkpoint-cadence
    # fleet checkpoints, and the drill resumes from the newest valid one —
    # the measured resume duration is recovery_mttr_ms, and the resumed
    # run's final state must be BIT-IDENTICAL to an uninterrupted twin
    # (asserted, not assumed). Never silently absent: recovery_status
    # always lands in the emitted JSON (the n1M_status discipline).
    recovery_n, recovery_waves, recovery_status = recovery_plan(
        platform, time.monotonic() - _START
    )
    recovery_fields = {}
    if recovery_n == 0:
        _mark(f"recovery stage not run: {recovery_status}")
    else:
        import tempfile

        from rapid_tpu.serving import (
            PoissonChurn as _RecChurn,
            SimulatedProcessKill,
            Supervisor,
            SupervisorFaultPlan,
            recovery as serving_recovery,
        )

        from contextlib import contextmanager

        @contextmanager
        def _no_persistent_cache():
            # SCOPED: the drill's executables must be FRESH compiles, never
            # deserialized from the persistent cache. Root-caused via this
            # very stage's bit-identity assertion (the sibling note in
            # tools/analysis/device_program.py covers the sharded flavor):
            # on this jaxlib's CPU backend, executables deserialized from
            # the cache corrupt the heap under donated executions —
            # sometimes a glibc double-free abort, sometimes SILENT
            # scribbling over unrelated live buffers (observed: the twin's
            # static key lanes diverging). The drill is the one bench
            # workload that *checks* bits, so it must not run poisoned; its
            # shapes are stage-unique, so the scoped disable guarantees
            # fresh compiles at a few seconds' cost.
            prev = None
            restore = False
            try:
                prev = jax.config.jax_compilation_cache_dir
                jax.config.update("jax_compilation_cache_dir", None)
                restore = True
            except Exception:  # noqa: BLE001 — no cache knob, nothing to scope
                pass
            try:
                yield
            finally:
                if restore:
                    jax.config.update("jax_compilation_cache_dir", prev)

        rec_rounds = _env_int("RAPID_TPU_BENCH_RECOVERY_ROUNDS", 4)
        rec_slots = recovery_n + 2 * recovery_waves
        rec_kill_after = recovery_waves // 2
        rec_every = max(1, recovery_waves // 3)

        def build_recovery_cluster(seed: int):
            vcr = VirtualCluster.create(
                recovery_n, n_slots=rec_slots, k=k_rings, h=9, l=4,
                cohorts=min(8, recovery_n), fd_threshold=fd_threshold,
                seed=seed, delivery_spread=delivery_spread,
            )
            vcr.assign_cohorts_roundrobin()
            return vcr

        with ledger.stage(
            "recovery", timeout_s=_stage_timeout("recovery"),
            n=recovery_n,
        ), _no_persistent_cache():
            with _heartbeat(f"recovery drill N={recovery_n}"):
                # Uninterrupted twin: the bit-identity oracle.
                twin = build_recovery_cluster(seed=8_000)
                twin_sup = Supervisor(twin, rounds_per_wave=rec_rounds)
                for wave in _RecChurn(
                    recovery_n, rec_slots, rate=2.0, seed=8_100
                ).waves(recovery_waves):
                    twin_sup.submit(wave)
                twin_sup.drain()
                # The drill: transient failure at wave 1, kill mid-schedule.
                ckpt_dir = tempfile.mkdtemp(prefix="rapid-recovery-")
                drill = build_recovery_cluster(seed=8_000)
                drill_sup = Supervisor(
                    drill, rounds_per_wave=rec_rounds,
                    checkpoint_dir=ckpt_dir, checkpoint_every=rec_every,
                    fault_plan=SupervisorFaultPlan(
                        transient_submit=((1, 1),),
                        kill_after_wave=rec_kill_after,
                    ),
                    ledger=ledger, ledger_stage="recovery",
                )
                churn = _RecChurn(recovery_n, rec_slots, rate=2.0, seed=8_100)
                killed_at = None
                try:
                    for wave_idx in range(recovery_waves):
                        drill_sup.submit(churn.wave())
                except SimulatedProcessKill as exc:
                    killed_at = exc.wave_index
                assert killed_at is not None, "drill kill never fired"
                t_rec = time.monotonic()
                resumed_sup, next_wave = serving_recovery.resume(
                    ckpt_dir, checkpoint_every=rec_every,
                    ledger=ledger, ledger_stage="recovery",
                )
                churn2 = serving_recovery.fast_forward(
                    _RecChurn(recovery_n, rec_slots, rate=2.0, seed=8_100),
                    next_wave,
                )
                for wave_idx in range(next_wave, recovery_waves):
                    resumed_sup.submit(churn2.wave())
                resumed = resumed_sup.drain()
                mttr_ms = resumed_sup.last_resume_ms
                resume_to_serving_ms = (time.monotonic() - t_rec) * 1000.0
                import jax as _jax

                bit_identical = bool(_jax.tree_util.tree_all(
                    _jax.tree_util.tree_map(
                        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()),
                        resumed_sup.target.state, twin.state,
                    )
                )) and resumed_sup.target.config_id == twin.config_id
                assert bit_identical, (
                    "resumed drill diverged from the uninterrupted twin"
                )
            recovery_fields = {
                "recovery_mttr_ms": round(mttr_ms, 3),
                "recovery_resume_to_serving_ms": round(
                    resume_to_serving_ms, 3
                ),
                "recovery_killed_after_wave": killed_at,
                "recovery_resumed_wave": next_wave,
                "recovery_waves": recovery_waves,
                "recovery_n": recovery_n,
                "recovery_checkpoints": int(
                    drill.metrics.counters.get("engine_recovery_checkpoints", 0)
                ),
                "recovery_retries": int(
                    drill.metrics.counters.get("engine_recovery_retries", 0)
                ),
                "recovery_replayed_cuts": resumed.cuts,
                "recovery_bit_identical": bit_identical,
            }
            _mark(
                f"recovery: killed after wave {killed_at}, resumed at wave "
                f"{next_wave} in {mttr_ms:.1f} ms (serving again in "
                f"{resume_to_serving_ms:.1f} ms), final state bit-identical"
            )

    # Compiled-program audit (ISSUE 8, analysis family 12): compile the
    # registered engine entrypoints at the fixed audit shapes ON THIS
    # PLATFORM and embed the per-entrypoint collective/memory table, so the
    # BENCH_r* trajectory carries the communication budget alongside the
    # latency numbers and tools/perfview.py can flag collective-count
    # drift between rounds. On TPU this is the first compiled-collective
    # evidence per round; the lockfile GATE (CPU-pinned) stays in the test
    # session — here the facts are recorded, not judged.
    with ledger.stage("hlo_audit", timeout_s=_stage_timeout("hlo_audit")):
        with _heartbeat("hlo audit compile"):
            hlo_audit = hlo_audit_summary()
        if "error" in hlo_audit:
            _mark(f"hlo audit unavailable: {hlo_audit['error']}")
        else:
            _mark(f"hlo audit: {len(hlo_audit)} entrypoints compiled")
        # Memory-footprint fields (ISSUE 13): bytes/member at this run's
        # geometry + the 100k->100M sizing table, status-stamped from the
        # audit's memory_analysis — never silently absent.
        mem_fields = memory_report(
            hlo_audit, n=n, k_rings=k_rings, cohorts=cohorts,
            use_pallas=use_pallas,
        )
        _mark(
            f"memory: {mem_fields['bytes_per_member']:.0f} B/member compact "
            f"vs {mem_fields['bytes_per_member_wide']:.0f} wide "
            f"({mem_fields['mem_status']}); 100M sizing "
            f"{mem_fields['mem_sizing']['100M']['compact_gb']:.0f} GB"
        )
        # Scaling-law cost axis (ISSUE 18): quiescent round cost +
        # fitted classes, riding the same stage (and its compiles).
        with _heartbeat("cost ladder compile"):
            cost_fields = cost_report()
        fit = cost_fields["cost_fit"]
        _mark(
            "cost fit: " + (
                fit["status"] if "status" in fit
                else f"{len(fit)} entrypoints classified"
            )
        )
        # Jaxpr provenance axis (ISSUE 19): observer-silence and
        # tenant-isolation verdicts plus the sparse-opportunity coverage,
        # riding the same stage (the byte join reuses its compiles).
        with _heartbeat("dataflow trace"):
            dataflow_fields = dataflow_summary()
        df = dataflow_fields["dataflow"]
        _mark(
            "dataflow: " + (
                df["status"] if df["status"] != "ok"
                else f"proofs ok, opportunity map covers "
                     f"{df['opportunity_coverage_pct']}% of quiescent bytes"
            )
        )

    # Opt-in jax.profiler capture (--profile DIR): one extra resolved churn
    # under utils/profiling.trace, as its own budgeted stage — TensorBoard/
    # Perfetto-grade device timelines when the operator asks for them,
    # zero cost otherwise.
    if profile_dir:
        from rapid_tpu.utils.profiling import trace

        with ledger.stage("profile", timeout_s=_stage_timeout("profile"), n=n):
            vc, _ = build(seed=999)
            vc.sync()
            with _heartbeat("profiled convergence"):
                with trace(profile_dir):
                    resolve_churn(vc)
            _mark(f"profile captured into {profile_dir}")

    value = min(samples)
    # Bounded log-bucketed histogram of the timed samples (the same
    # fixed-schedule instrument the membership service uses for its phase
    # SLOs, utils/histogram.py): the bench trajectory records quantiles —
    # p50/p90/p99/max plus mergeable bucket counts — not just the min/mean,
    # so cross-round comparisons can see tail behavior.
    from rapid_tpu.utils.histogram import LogHistogram

    sample_hist = LogHistogram()
    for s in samples:
        sample_hist.observe(s)
    engine_compiles = engine_telemetry.compile_snapshot()
    result = {
        "metric": f"churn_resolution_ms_n{n}_churn{int(churn_frac * 100)}pct",
        "value": round(value, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_target_ms / value, 3),
        "platform": platform,
        # The HEADLINE scale number (ROADMAP item 1): 1M members, 1% crash,
        # one single-dispatch convergence. Never silently absent —
        # n1M_status says exactly what the point is when the value itself
        # is missing ("ramped:<n>" = CPU stage-path exercise at a small N,
        # reported under xl_point_ms; "skipped-budget"; "suppressed").
        "n1M_status": xl_status,
        **(
            {"n1M_crash1pct_ms": round(xl_ms, 3), "lanes_1m": lanes_xl}
            if xl_ms is not None and xl_n == n_headline
            else {}
        ),
        **(
            {"xl_point_ms": round(xl_ms, 3), "xl_n": xl_n}
            if xl_ms is not None and xl_n != n_headline
            else {}
        ),
        **({"xl_device_memory": xl_memory} if xl_memory is not None else {}),
        # The opt-in stretch point (RAPID_TPU_BENCH_STRETCH): first-class
        # only at the named 10M goal, generic otherwise (mutually
        # exclusive, like the n1M_crash1pct_ms / xl_point_ms pair).
        **(
            {"n10M_crash1pct_ms": round(stretch_ms, 3)}
            if stretch_ms is not None and stretch_n == 10_000_000
            else {"stretch_ms": round(stretch_ms, 3), "stretch_n": stretch_n}
            if stretch_ms is not None
            else {}
        ),
        # Multi-tenant fleet point (ISSUE 10): total view changes committed
        # across B independent clusters per second of the ONE lockstep
        # dispatch. Never silently absent — tenant_fleet_status says
        # exactly what the point is when the value itself is missing
        # ("ramped:BxN" = CPU stage-path exercise; "skipped-budget";
        # "suppressed").
        "tenant_fleet_status": fleet_status,
        **(
            {
                "tenant_view_changes_per_sec": round(fleet_vcps, 1),
                "fleet_tenants": fleet_b,
                "fleet_tenant_members": fleet_n,
                "fleet_view_changes": fleet_cuts_total,
                "fleet_wall_ms": round(fleet_wall_ms, 3),
            }
            if fleet_vcps is not None
            else {}
        ),
        # Device telemetry plane, fleet half (ISSUE 16): the pooled and
        # per-tenant conflict rates from the lanes the fleet wave carried.
        **(
            {
                "tenant_conflict_rate": round(
                    fleet_activity["conflict_rate"], 6
                ),
                "tenant_conflict_rates": fleet_conflict_rates,
                "fleet_fast_path_share": round(
                    fleet_activity["fast_path_share"], 4
                ),
            }
            if fleet_activity is not None
            else {}
        ),
        **({"fleet_device_memory": fleet_memory} if fleet_memory is not None else {}),
        # Streaming serving point (ISSUE 11): sustained view-changes/sec,
        # p99 alert->commit, and overlap efficiency through the pipelined
        # dispatch path over BOTH serving shapes (single cluster + fleet).
        # Never silently absent — stream_status says exactly what the point
        # is when the values themselves are missing ("ramped:WxN" = CPU
        # pipeline exercise; "skipped-budget"; "suppressed").
        "stream_status": stream_status,
        **{k: v for k, v in stream_fields.items() if v is not None},
        # Device telemetry plane status (ISSUE 16): never silently absent —
        # see activity_status for the policy.
        "activity_status": activity_status(stream_fields, stream_status),
        # Round-trace ring status (ISSUE 17): never silently absent — see
        # trace_status for the policy.
        "trace_status": trace_status(stream_fields, stream_status),
        **({"stream_device_memory": stream_memory} if stream_memory is not None else {}),
        # Adversarial-chaos point (ISSUE 12): hostile scenarios resolved
        # (and oracle-checked clean) per second of batched fleet dispatch.
        # Never silently absent — chaos_status says exactly what the point
        # is when the value itself is missing ("ramped:Bx12" = CPU
        # stage-path exercise; "skipped-budget"; "suppressed").
        "chaos_status": chaos_status,
        **{k: v for k, v in chaos_fields.items() if v is not None},
        # Self-healing drill point (ISSUE 15): MTTR of the deterministic
        # checkpoint-resume after an injected mid-stream kill, with the
        # bit-identity verdict beside it. Never silently absent —
        # recovery_status says exactly what the point is when the value
        # itself is missing ("ramped:WxN" = CPU drill; "skipped-budget";
        # "suppressed").
        "recovery_status": recovery_status,
        **{k: v for k, v in recovery_fields.items() if v is not None},
        "samples_ms": [round(s, 3) for s in samples],
        "churn_resolution_hist": sample_hist.summary(),
        "view_changes": cuts_per_sample,
        "n_members": n,
        "joins": n_join,
        "crashes": n_crash,
        "cohorts": cohorts,
        "delivery_spread": delivery_spread,
        # Derived throughput rates at the engine's actual delivery grain
        # (per-cohort) — unit-audited in derived_metrics, plausibility
        # bounds pinned by tests/test_bench_snapshot.py.
        **derived_metrics(
            n=n, n_join=n_join, n_crash=n_crash, k_rings=k_rings,
            cohorts=cohorts, value_ms=value,
        ),
        "device_rtt_ms": round(rtt_ms, 3),
        # Compiled-program audit table (per-entrypoint collective/memory
        # facts at the fixed audit shapes, or {"error": ...}): the
        # trajectory's communication-budget axis — perfview flags
        # collective-count drift between rounds from this.
        "hlo_audit": hlo_audit,
        # State-compaction memory axis (ISSUE 13): bytes/member under the
        # wide/compact/packed layouts, the run's total state bytes, the
        # 100k->100M deployment sizing, and the never-silently-absent
        # mem_status — perfview renders the MEM column from these.
        **mem_fields,
        # Scaling-law cost axis (ISSUE 18): the zero-churn round's frozen
        # per-round cost + fitted per-entrypoint scaling classes (or the
        # named suppressed/unavailable status) — perfview renders the
        # COSTFIT column from these.
        **cost_fields,
        # Jaxpr dataflow provenance axis (ISSUE 19): proof verdicts + the
        # sparse-opportunity coverage (or the named suppressed/unavailable
        # status) — perfview renders the OPPTY column and the
        # dataflow-missing trust flag from these.
        **dataflow_fields,
        # Engine-tier provenance for the trajectory: how much compile time
        # this run paid and whether the persistent cache carried it.
        "compiles": engine_compiles["compiles"],
        "compile_ms_total": round(float(engine_compiles["compile_ms"]["sum"]), 3),
        "persistent_cache_hits": engine_compiles["persistent_cache_hits"],
        "persistent_cache_misses": engine_compiles["persistent_cache_misses"],
        # Adverse-network axis: the same churn under the sim
        # subsystem's 5%-loss schedule (None when budget-skipped).
        **(
            {
                "churn_under_loss_ms": round(loss_ms, 3),
                "loss_permille": loss_permille,
                "loss_delivery_spread": loss_knobs["delivery_spread"],
            }
            if loss_ms is not None
            else {}
        ),
        # Delivery-kernel tile width in effect for the main workload
        # (autotune provenance); the headline fields near the top carry the
        # 1M width when the full point ran.
        "pallas_lanes": lanes_main,
    }
    ledger.emit(LedgerEvent.METRIC, **result)
    print(json.dumps(result), flush=True)


# ---------------------------------------------------------------------------
# Watchdog orchestration (parent).
# ---------------------------------------------------------------------------


class _LedgerTail:
    """Incremental reader over the (shared, append-only, possibly
    multi-run) ledger file: each ``poll()`` parses only the bytes appended
    since the last one and keeps the events of ONE run — the watchdog's
    1 s loop must not re-parse a file that other runs have grown, and must
    never mistake a previous run's stages for this run's."""

    def __init__(self, path: str, run_id: str) -> None:
        self._path = path
        self._run_id = run_id
        self._offset = 0
        self._buf = b""
        self.events: list = []

    def poll(self) -> list:
        try:
            with open(self._path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
        except OSError:
            return self.events
        self._offset += len(chunk)
        self._buf += chunk
        while b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn/foreign line: same tolerance as read_ledger
            if (
                isinstance(record, dict)
                and "event" in record
                and record.get("run_id") == self._run_id
            ):
                self.events.append(record)
        return self.events


def _run_events(path: str, run_id: str) -> list:
    """This run's events from a ledger file that may hold many runs (the
    default bench_ledger.jsonl accumulates across invocations)."""
    from rapid_tpu.utils.ledger import read_ledger

    events, _ = read_ledger(path)
    return [e for e in events if e.get("run_id") == run_id]


def _run_child_watchdogged(ledger) -> bool:
    """Run the workload in a child on the accelerator; True iff it printed
    its JSON line. Liveness = progress marks: a silent child past the idle
    budget (or the hard deadline, or the current ledger stage's own
    timeout) is abandoned, not waited on — a wedged axon client can survive
    SIGKILL in an uninterruptible device call, so the reap itself must be
    abandonable."""
    from rapid_tpu.utils.ledger import STAGE_NAMES, LedgerEvent, open_stage

    first_mark_timeout = _env_int("RAPID_TPU_BENCH_INIT_TIMEOUT_S", 240)
    idle_timeout = _env_int("RAPID_TPU_BENCH_IDLE_TIMEOUT_S", 900)
    hard_deadline = _env_int("RAPID_TPU_BENCH_DEADLINE_S", 2700)
    heartbeat_gap_floor_s = 60.0

    env = dict(os.environ)
    env["RAPID_TPU_BENCH_CHILD"] = "1"
    env["RAPID_TPU_BENCH_LEDGER"] = ledger.path
    env["RAPID_TPU_BENCH_RUN_ID"] = ledger.run_id
    env["RAPID_TPU_BENCH_LEDGER_T0"] = repr(ledger.t0)
    child = subprocess.Popen(
        [sys.executable, "-u", os.path.abspath(__file__), *sys.argv[1:]],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
    )
    os.set_blocking(child.stdout.fileno(), False)
    os.set_blocking(child.stderr.fileno(), False)

    got_json = False
    saw_mark = False
    last_alive = time.monotonic()
    start = last_alive
    cpu_at_last_alive = 0.0
    buf_out = b""
    buf_err = b""
    tail = _LedgerTail(ledger.path, ledger.run_id)
    # Per-stage budget tracking: (begin seq, first time the parent saw it).
    stage_seen: tuple = ()

    def safe_stage(record) -> "str | None":
        """A stage name read back from the FILE, re-emittable only if it is
        in the current registered vocabulary (the strict emit would raise
        on a foreign writer's stage; the file is untrusted input)."""
        name = record.get("stage") if record else None
        return name if name in STAGE_NAMES else None
    while True:
        alive_before = last_alive
        for stream, is_err in ((child.stdout, False), (child.stderr, True)):
            chunk = None
            try:
                chunk = stream.read()
            except (BlockingIOError, OSError):
                pass
            if not chunk:
                continue
            last_alive = time.monotonic()
            if is_err:
                buf_err += chunk
                while b"\n" in buf_err:
                    line, buf_err = buf_err.split(b"\n", 1)
                    text = line.decode(errors="replace")
                    print(text, file=sys.stderr, flush=True)
                    if text.startswith("bench["):
                        saw_mark = True
            else:
                buf_out += chunk
                while b"\n" in buf_out:
                    line, buf_out = buf_out.split(b"\n", 1)
                    text = line.decode(errors="replace").strip()
                    if text.startswith("{") and '"metric"' in text:
                        print(text, flush=True)
                        got_json = True
        # Marks only appear at stage boundaries; between them (e.g. a long
        # XLA compile) the child's CPU clock is the liveness signal — a
        # compiling child burns CPU continuously. Liveness needs >= 1s of
        # ACCUMULATED CPU since the last liveness event: a wedged axon
        # client still ticks a few ms/min of heartbeat-thread CPU, and a
        # single-tick test would let that trickle hold the watchdog open
        # forever (observed).
        cpu_s = _child_cpu_seconds(child.pid)
        if cpu_s is not None and cpu_s - cpu_at_last_alive >= 1.0:
            cpu_at_last_alive = cpu_s
            last_alive = time.monotonic()
        # The ledger is the stage-level truth: track the open stage and its
        # own budget, and record recovered liveness gaps (a tunnel that
        # stalled for minutes then resumed is a diagnosable event even when
        # the run ultimately succeeds). Incremental + run-scoped: only newly
        # appended bytes are parsed, and only THIS run's events count.
        current = open_stage(tail.poll())
        if current is not None:
            key = (current.get("seq"), current.get("pid"))
            if not stage_seen or stage_seen[0] != key:
                stage_seen = (key, time.monotonic(), current)
        else:
            stage_seen = ()
        if last_alive > alive_before:
            gap_s = last_alive - alive_before
            if gap_s >= heartbeat_gap_floor_s:
                ledger.emit(
                    LedgerEvent.HEARTBEAT_GAP,
                    stage=safe_stage(current),
                    gap_s=round(gap_s, 1),
                )
        code = child.poll()
        if code is not None:
            _flush_partials(buf_out, buf_err)
            # A child that printed its JSON line succeeded even if the flaky
            # axon client then crashed interpreter teardown (nonzero exit):
            # re-running on CPU would emit a SECOND JSON line.
            return got_json
        now = time.monotonic()
        # Until the first mark (devices initialized), a tight budget: the
        # wedged-tunnel signature is exactly "init never completes".
        budget = idle_timeout if saw_mark else first_mark_timeout
        stage_overrun = None
        if stage_seen:
            _, seen_at, begin = stage_seen
            timeout_s = begin.get("timeout_s")
            if timeout_s and now - seen_at > float(timeout_s):
                stage_overrun = (begin.get("stage"), float(timeout_s))
        if now - last_alive > budget or now - start > hard_deadline or stage_overrun:
            if stage_overrun:
                why = (f"stage {stage_overrun[0]!r} exceeded its "
                       f"{stage_overrun[1]:.0f}s budget")
            elif now - start > hard_deadline:
                why = "hard deadline"
            else:
                why = "went silent"
            print(
                f"bench: accelerator child {why} "
                f"({now - start:.0f}s elapsed, {now - last_alive:.0f}s idle); abandoning",
                file=sys.stderr,
                flush=True,
            )
            overrun_name = (
                stage_overrun[0] if stage_overrun and stage_overrun[0] in STAGE_NAMES
                else None
            )
            ledger.emit(
                LedgerEvent.WATCHDOG_KILL,
                stage=overrun_name or safe_stage(current),
                reason=why,
                elapsed_s=round(now - start, 1),
                idle_s=round(now - last_alive, 1),
            )
            child.kill()
            try:
                child.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass  # unreapable (D-state) child: abandon it
            _flush_partials(buf_out, buf_err)
            return got_json
        time.sleep(1)


def _flush_partials(buf_out: bytes, buf_err: bytes) -> None:
    """Surface any final newline-less fragments (a segfault or OOM kill cuts
    the child mid-line, and that fragment is usually the best diagnostic)."""
    for buf in (buf_out, buf_err):
        if buf.strip():
            print(buf.decode(errors="replace"), file=sys.stderr, flush=True)


def _child_cpu_seconds(pid: int):
    """utime+stime of the child in seconds, or None (non-Linux / gone)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            fields = f.read().split(b") ", 1)[1].split()
        return (int(fields[11]) + int(fields[12])) / os.sysconf("SC_CLK_TCK")
    except (OSError, IndexError, ValueError):
        return None


def _git_head_rev(root: str):
    """Short HEAD rev of the repo at ``root``, or None when unavailable —
    THE definition lives in rapid_tpu.utils.ledger (the run ledger's
    provenance stamp); this wrapper keeps bench's snapshot path and its
    tests on the same one."""
    from rapid_tpu.utils.ledger import git_head_rev

    return git_head_rev(root)


# Source paths whose content determines what bench.py measures; commits that
# touch none of these (evidence captures, docs, tests) do not stale a
# snapshot. These are also the run ledger's code-hash roots.
_MEASUREMENT_PATHS = ("bench.py", "rapid_tpu", "native")


def _snapshot_is_stale(root: str, snap_rev, head_rev) -> bool:
    """True when the snapshot measured different CODE than HEAD. Bare rev
    inequality is not enough: the evidence watcher commits its own capture
    right after stamping it, advancing HEAD past the captured rev with a
    byte-identical source tree — so when revs differ, the verdict comes from
    diffing the measurement-relevant paths between them. Unknown revs (or a
    snapshot rev no longer in the repo) are stale: provenance that cannot be
    checked is never trusted."""
    # snap_rev comes from an evidence JSON file: only a hex-looking string is
    # allowed into the git argv (a non-string would raise past the except
    # clause below; a leading-dash string would parse as a git option).
    if not isinstance(snap_rev, str) or not re.fullmatch(r"[0-9a-fA-F]{7,40}", snap_rev):
        return True
    if head_rev is None:
        return True
    if snap_rev == head_rev:
        return False
    try:
        rc = subprocess.run(
            ["git", "diff", "--quiet", snap_rev, head_rev, "--", *_MEASUREMENT_PATHS],
            cwd=root, timeout=10,
        ).returncode
    except (OSError, subprocess.TimeoutExpired):
        return True
    return rc != 0  # nonzero: paths differ, or a rev is unknown to git


def _emit_tpu_snapshot(ledger=None) -> bool:
    """When the live accelerator attempt wedges AND the caller explicitly
    allowed replay (--allow-snapshot), fall back to the most recent TPU
    measurement captured DURING a live tunnel window by
    tools/capture_tpu_evidence.sh (committed under evidence/<round>/bench.json
    with a `captured_at` stamp). The tunnel wedges for hours at a time, so
    the driver's capture window is often dead even though the hardware number
    exists; the snapshot is the same bench.py workload, same shapes, emitted
    with full provenance so a reader can tell a replayed measurement from a
    live one — and the run ledger records the replay (snapshot_replay event)
    so the trajectory can never silently absorb it. True iff a snapshot was
    emitted.

    Code provenance: the capture script stamps `git_rev` into each capture;
    the replay diffs the measurement-relevant source paths between that rev
    and HEAD (_snapshot_is_stale). When they differ — or provenance cannot be
    checked — the snapshot measured DIFFERENT CODE: the emitted metric is
    renamed with a `_snapshot` suffix, `stale_code: true` is set, and
    `vs_baseline` is demoted to `vs_baseline_at_capture`, so no consumer can
    mistake a historical number for a measurement of HEAD."""
    candidates = []
    explicit = os.environ.get("RAPID_TPU_BENCH_SNAPSHOT")
    root = os.path.dirname(os.path.abspath(__file__))
    paths = [explicit] if explicit else sorted(
        glob.glob(os.path.join(root, "evidence", "*", "bench.json"))
    )
    requested_n = _env_int("RAPID_TPU_BENCH_N", 100_000)
    for path in paths:
        try:
            with open(path) as f:
                data = json.loads(f.read().strip() or "null")
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(data, dict) or data.get("platform") != "tpu":
            continue
        if "metric" not in data or "value" not in data:
            continue
        if data.get("n_members") != requested_n:
            # A snapshot only stands in for the SAME workload: a smoke run
            # at RAPID_TPU_BENCH_N=2000 must not replay the 100K capture.
            continue
        # Order by embedded capture stamp; fall back to file mtime for
        # pre-stamp captures (round 2's).
        stamp = data.get("captured_at") or time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(path))
        )
        candidates.append((stamp, path, data))
    if not candidates:
        return False
    stamp, path, data = max(candidates)
    data.setdefault("captured_at", stamp)
    data["capture"] = "session_snapshot"
    data["snapshot_path"] = os.path.relpath(path, root)
    data["live_attempt"] = "wedged"
    head_rev = _git_head_rev(root)
    snap_rev = data.get("git_rev")
    if head_rev:
        data["head_rev"] = head_rev
    stale = _snapshot_is_stale(root, snap_rev, head_rev)
    data["stale_code"] = stale
    if stale:
        # The snapshot measured a different commit than HEAD (or its commit
        # is unrecorded): rename the metric and demote the baseline ratio so
        # the replayed number can never pass as a measurement of current code.
        data["metric"] = str(data["metric"]) + "_snapshot"
        if "vs_baseline" in data:
            data["vs_baseline_at_capture"] = data.pop("vs_baseline")
    if ledger is not None:
        from rapid_tpu.utils.ledger import LedgerEvent

        ledger.emit(
            LedgerEvent.SNAPSHOT_REPLAY,
            snapshot_path=data["snapshot_path"],
            captured_at=data["captured_at"],
            git_rev=snap_rev,
            stale_code=stale,
        )
    print(
        f"bench: live accelerator wedged; replaying TPU snapshot {data['snapshot_path']} "
        f"(captured_at {data['captured_at']}, git_rev {snap_rev or 'unknown'}"
        + (f", STALE vs HEAD {head_rev}" if stale else ", matches HEAD")
        + ")",
        file=sys.stderr,
        flush=True,
    )
    print(json.dumps(data), flush=True)
    return True


def _parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        description="rapid_tpu convergence benchmark (see module docstring)"
    )
    parser.add_argument(
        "--ledger", default=None, metavar="PATH",
        help="append-only JSONL run ledger (default: $RAPID_TPU_BENCH_LEDGER "
             "or ./bench_ledger.jsonl); render with tools/perfview.py",
    )
    parser.add_argument(
        "--allow-snapshot", action="store_true",
        default=_env_flag("RAPID_TPU_BENCH_ALLOW_SNAPSHOT"),
        help="permit replaying a committed TPU evidence snapshot when the "
             "live accelerator wedges (always marked in the ledger and the "
             "emitted JSON); without it a wedge exits nonzero",
    )
    parser.add_argument(
        "--cpu-fallback", action="store_true",
        default=_env_flag("RAPID_TPU_BENCH_CPU_FALLBACK"),
        help="re-run the workload on CPU when the accelerator wedges (a real "
             "measurement, clearly labeled platform=cpu)",
    )
    parser.add_argument(
        "--profile", default=os.environ.get("RAPID_TPU_BENCH_PROFILE") or None,
        metavar="DIR",
        help="capture a jax.profiler trace of one resolved churn into DIR "
             "(opt-in 'profile' ledger stage; view with TensorBoard/Perfetto)",
    )
    return parser.parse_args(argv)


def _ledger_path(args: argparse.Namespace) -> str:
    return (
        args.ledger
        or os.environ.get("RAPID_TPU_BENCH_LEDGER")
        or "bench_ledger.jsonl"
    )


def main() -> int:
    from rapid_tpu.utils.ledger import (
        LedgerEvent,
        RunLedger,
        last_completed_stage,
        provenance,
    )

    args = _parse_args()
    root = os.path.dirname(os.path.abspath(__file__))
    if _env_flag("RAPID_TPU_BENCH_CHILD") or os.environ.get("JAX_PLATFORMS") == "cpu":
        # Workload mode: the watchdogged accelerator child, a CPU re-exec
        # continuation, or a direct CPU invocation. Continuations join the
        # parent's run (its id arrives via env); a direct invocation owns
        # the whole run and brackets it itself.
        inherited = os.environ.get("RAPID_TPU_BENCH_RUN_ID")
        try:
            # The run's shared t_s epoch rides beside its id: every process
            # of one run (parent, attempt children, fallback continuation)
            # writes on one timeline.
            t0 = float(os.environ["RAPID_TPU_BENCH_LEDGER_T0"])
        except (KeyError, ValueError):
            t0 = None
        ledger = RunLedger(_ledger_path(args), run_id=inherited, t0=t0)
        owns_run = inherited is None
        if owns_run:
            ledger.emit(LedgerEvent.RUN_BEGIN, mode="inline",
                        argv=sys.argv[1:], **provenance(root, _MEASUREMENT_PATHS))
        try:
            run_workload(ledger, profile_dir=args.profile)
        except BaseException as exc:
            ledger.emit(LedgerEvent.RUN_FAIL, error=repr(exc),
                        last_completed_stage=last_completed_stage(
                            _run_events(ledger.path, ledger.run_id)))
            raise
        if owns_run:
            ledger.emit(LedgerEvent.RUN_END, outcome="completed")
        elif not _env_flag("RAPID_TPU_BENCH_CHILD"):
            # The --cpu-fallback execve continuation: the watchdog parent
            # that would have closed the run replaced itself with this
            # process, so the successful fallback must close it — or the
            # ledger ends at run_fail and the run reads as FAILED. (The
            # watchdogged CHILD must not: its parent is still alive and
            # owns the run's outcome.)
            ledger.emit(LedgerEvent.RUN_END, outcome="cpu_fallback")
        return 0

    ledger = RunLedger(_ledger_path(args))
    ledger.emit(LedgerEvent.RUN_BEGIN, mode="watchdogged", argv=sys.argv[1:],
                **provenance(root, _MEASUREMENT_PATHS))
    # Bounded retry: transient tunnel hiccups recover between attempts
    # (observed); only a persistent wedge should cost the TPU number.
    attempts = max(1, _env_int("RAPID_TPU_BENCH_ATTEMPTS", 2))
    for attempt in range(attempts):
        ledger.emit(LedgerEvent.ATTEMPT_BEGIN, attempt=attempt + 1,
                    attempts=attempts)
        ok = _run_child_watchdogged(ledger)
        ledger.emit(LedgerEvent.ATTEMPT_END, attempt=attempt + 1, got_json=ok)
        if ok:
            ledger.emit(LedgerEvent.RUN_END, outcome="live")
            return 0
        if attempt + 1 < attempts:
            print(
                f"bench: accelerator attempt {attempt + 1}/{attempts} failed; retrying",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(15)
    last_stage = last_completed_stage(_run_events(ledger.path, ledger.run_id))
    ledger.emit(LedgerEvent.RUN_FAIL, outcome="wedged",
                last_completed_stage=last_stage)
    if _env_flag("RAPID_TPU_BENCH_NO_FALLBACK"):
        # Sweep mode: a dead accelerator must be an EXPLICIT hole in the
        # curve (and cost no CPU-fallback minutes of a live window), never
        # a silently missing point.
        print(json.dumps({
            "metric": f"churn_resolution_ms_n{_env_int('RAPID_TPU_BENCH_N', 100_000)}",
            "error": "accelerator_unavailable",
            "n_members": _env_int("RAPID_TPU_BENCH_N", 100_000),
        }), flush=True)
        return 0
    if (
        args.allow_snapshot
        and not _env_flag("RAPID_TPU_BENCH_NO_SNAPSHOT")
        and _emit_tpu_snapshot(ledger)
    ):
        # The replay closed the run (rc 0): without this, the ledger's
        # latest terminal event stays run_fail and the run reads FAILED.
        ledger.emit(LedgerEvent.RUN_END, outcome="snapshot_replay")
        return 0
    if args.cpu_fallback:
        print("bench: falling back to CPU", file=sys.stderr, flush=True)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["RAPID_TPU_BENCH_LEDGER"] = ledger.path
        env["RAPID_TPU_BENCH_RUN_ID"] = ledger.run_id
        env["RAPID_TPU_BENCH_LEDGER_T0"] = repr(ledger.t0)
        env.pop("RAPID_TPU_BENCH_CHILD", None)
        os.execve(sys.executable, [sys.executable] + sys.argv, env)
    # Loud failure, the default: the accelerator wedged, no stand-in was
    # authorized — say exactly how far the run got and exit nonzero so no
    # driver can mistake this round for a measurement.
    print(
        "bench: accelerator wedged and no fallback authorized "
        f"(last completed stage: {last_stage or 'none'}; ledger: {ledger.path}); "
        "pass --allow-snapshot to replay committed TPU evidence or "
        "--cpu-fallback to re-run on CPU",
        file=sys.stderr,
        flush=True,
    )
    print(json.dumps({
        "metric": f"churn_resolution_ms_n{_env_int('RAPID_TPU_BENCH_N', 100_000)}",
        "error": "accelerator_wedged",
        "last_completed_stage": last_stage,
        "ledger": ledger.path,
    }), flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
