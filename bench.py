"""Benchmark: view-change convergence wall-clock for the TPU virtual-cluster
engine.

Scenario (BASELINE.json config 4 scaled to the available chip): N virtual
members, 1% concurrent crash faults; measure wall-clock from fault injection
to a committed view change that removes exactly the faulty set. The
reference's corresponding number (paper Fig. 8): 10 concurrent crashes at
N=1000 resolve in one consensus decision, with multi-second detection; the
BASELINE target is <500 ms at N=100K virtual nodes.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _ensure_responsive_backend() -> None:
    """The axon tunnel backend can wedge such that ``jax.devices()`` blocks
    forever (observed after killed mid-compile sessions). Probe device init in
    a subprocess with a timeout; if it hangs or fails, re-exec on CPU so the
    bench always emits its JSON line instead of hanging the driver.

    Cost on a healthy backend: one extra device init (a few seconds), paid
    once per bench invocation — cheap insurance against an unbounded hang.
    Skip with RAPID_TPU_BENCH_NO_PROBE=1."""
    if os.environ.get("RAPID_TPU_BENCH_NO_PROBE") or os.environ.get("JAX_PLATFORMS") == "cpu":
        return
    detail = "probe timed out"
    # Manual poll loop instead of subprocess.run: run()'s TimeoutExpired path
    # does kill()+wait() with no bound, and a child wedged in an
    # uninterruptible driver call (the exact failure this guards against)
    # survives SIGKILL — the reap must be abandonable.
    probe = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        code = probe.poll()
        if code is not None:
            if code == 0:
                return
            # Surface the real diagnostic: a nonzero exit is a misconfigured
            # backend (missing/broken driver), not a wedge.
            try:
                detail = (probe.stderr.read() or b"").decode(errors="replace")[-800:]
            except Exception:  # noqa: BLE001 — diagnostics are best-effort
                pass
            break
        time.sleep(1)
    else:
        probe.kill()
        try:
            probe.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass  # unreapable (D-state) child: abandon it, fall back anyway
    print(
        f"bench: accelerator backend unresponsive; falling back to CPU ({detail})",
        file=sys.stderr,
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RAPID_TPU_BENCH_NO_PROBE"] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def main() -> None:
    _ensure_responsive_backend()
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # sitecustomize imported jax before us; env alone is too late.
        from rapid_tpu.utils.platform import force_platform

        force_platform("cpu")
    import numpy as np

    from rapid_tpu.utils._native import ensure_built

    ensure_built()  # compile the native host library outside any event loop

    from rapid_tpu.models.virtual_cluster import VirtualCluster

    n = 100_000
    crash_frac = 0.01
    fd_threshold = 3
    k_rings = 10
    baseline_target_ms = 500.0

    platform = jax.devices()[0].platform

    def build():
        # One receiver cohort: crash faults never diverge healthy receivers.
        # The cut detector's merge+classify runs through the Pallas kernel.
        vc = VirtualCluster.create(
            n, k=k_rings, h=9, l=4, cohorts=1, fd_threshold=fd_threshold, seed=0,
            use_pallas=(platform == "tpu"),
        )
        rng = np.random.default_rng(7)
        victims = rng.choice(n, size=int(n * crash_frac), replace=False)
        return vc, victims

    # Warm-up: compile the single-dispatch convergence loop (steady-state
    # rounds + the view-change branch).
    vc, victims = build()
    vc.crash(victims)
    rounds, decided, _ = vc.run_to_decision(max_steps=fd_threshold + 8)
    assert decided, "warm-up did not converge"

    # Timed runs on fresh state (same shapes -> cached executables).
    samples = []
    for _ in range(3):
        vc, victims = build()
        vc.crash(victims)
        # Real barrier: state upload/init must complete before the clock
        # starts (block_until_ready is advisory on tunnel backends).
        vc.sync()
        start = time.perf_counter()
        rounds, decided, _ = vc.run_to_decision(max_steps=fd_threshold + 8)
        jax.block_until_ready(vc.state.alive)
        elapsed_ms = (time.perf_counter() - start) * 1000.0
        assert decided, "bench run did not converge"
        assert vc.membership_size == n - len(victims)
        assert not vc.alive_mask[victims].any()
        samples.append(elapsed_ms)

    # Fixed device<->host round-trip latency of this environment (the axon
    # tunnel); a co-located deployment would not pay it.
    import jax.numpy as jnp

    probe = jax.jit(lambda a: a + 1)
    int(probe(jnp.int32(1)))
    t0 = time.perf_counter()
    int(probe(jnp.int32(2)))
    rtt_ms = (time.perf_counter() - t0) * 1000.0

    # Optional XL sample: 1M virtual nodes, 1% crash (10K concurrent faults in
    # one cut). Adds ~2-3 min of XLA compile; enable with RAPID_TPU_BENCH_XL=1.
    xl_ms = None
    if os.environ.get("RAPID_TPU_BENCH_XL"):
        n_xl = 1_000_000
        vcx = VirtualCluster.create(
            n_xl, k=10, h=9, l=4, cohorts=1, fd_threshold=fd_threshold, seed=0,
            use_pallas=(platform == "tpu"),
        )
        vcx.crash(np.random.default_rng(7).choice(n_xl, size=n_xl // 100, replace=False))
        vcx.sync()
        vcx.run_to_decision(max_steps=fd_threshold + 8)  # warm-up/compile
        vcx = VirtualCluster.create(
            n_xl, k=10, h=9, l=4, cohorts=1, fd_threshold=fd_threshold, seed=1,
            use_pallas=(platform == "tpu"),
        )
        vcx.crash(np.random.default_rng(8).choice(n_xl, size=n_xl // 100, replace=False))
        vcx.sync()
        t0 = time.perf_counter()
        _, decided_xl, _ = vcx.run_to_decision(max_steps=fd_threshold + 8)
        xl_ms = (time.perf_counter() - t0) * 1000.0
        assert decided_xl and vcx.membership_size == n_xl - n_xl // 100

    value = min(samples)
    print(
        json.dumps(
            {
                "metric": f"view_change_convergence_ms_n{n}_crash{int(crash_frac * 100)}pct",
                "value": round(value, 3),
                "unit": "ms",
                "vs_baseline": round(baseline_target_ms / value, 3),
                "platform": platform,
                "rounds": rounds,
                "samples_ms": [round(s, 3) for s in samples],
                "n_members": n,
                "faults": int(n * crash_frac),
                # Logical alert deliveries during convergence: every fired
                # edge alert (faults x K rings) reaches all N receivers —
                # the BASELINE's alerts/sec axis.
                "alert_deliveries_per_sec": round(
                    int(n * crash_frac) * k_rings * n / (value / 1000.0), 0
                ),
                "device_rtt_ms": round(rtt_ms, 3),
                **({"n1M_crash1pct_ms": round(xl_ms, 3)} if xl_ms is not None else {}),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
