"""Benchmark: view-change convergence wall-clock for the TPU virtual-cluster
engine.

Scenario (BASELINE.json config 4 scaled to the available chip): N virtual
members, 1% concurrent crash faults; measure wall-clock from fault injection
to a committed view change that removes exactly the faulty set. The
reference's corresponding number (paper Fig. 8): 10 concurrent crashes at
N=1000 resolve in one consensus decision, with multi-second detection; the
BASELINE target is <500 ms at N=100K virtual nodes.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    import jax
    import numpy as np

    from rapid_tpu.models.virtual_cluster import VirtualCluster

    n = 100_000
    crash_frac = 0.01
    fd_threshold = 3
    baseline_target_ms = 500.0

    platform = jax.devices()[0].platform

    def build():
        vc = VirtualCluster.create(n, k=10, h=9, l=4, fd_threshold=fd_threshold, seed=0)
        rng = np.random.default_rng(7)
        victims = rng.choice(n, size=int(n * crash_frac), replace=False)
        return vc, victims

    # Warm-up: compile both the steady-state round and the view-change branch.
    vc, victims = build()
    vc.crash(victims)
    rounds, events = vc.run_until_converged(max_steps=fd_threshold + 8)
    assert events is not None, "warm-up did not converge"

    # Timed runs on fresh state (same shapes -> cached executables).
    samples = []
    for _ in range(3):
        vc, victims = build()
        vc.crash(victims)
        jax.block_until_ready(vc.state.alive)
        start = time.perf_counter()
        rounds, events = vc.run_until_converged(max_steps=fd_threshold + 8)
        jax.block_until_ready(vc.state.alive)
        elapsed_ms = (time.perf_counter() - start) * 1000.0
        assert events is not None, "bench run did not converge"
        assert vc.membership_size == n - len(victims)
        assert not vc.alive_mask[victims].any()
        samples.append(elapsed_ms)

    value = min(samples)
    print(
        json.dumps(
            {
                "metric": f"view_change_convergence_ms_n{n}_crash{int(crash_frac * 100)}pct",
                "value": round(value, 3),
                "unit": "ms",
                "vs_baseline": round(baseline_target_ms / value, 3),
                "platform": platform,
                "rounds": rounds,
                "samples_ms": [round(s, 3) for s in samples],
                "n_members": n,
                "faults": int(n * crash_frac),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
